"""Shared autoregressive generation machinery (KV-cache serving path).

Used by the Llama and GPT families. Design (verified on-chip, M25):
- prefill is ONE jitted call (eager per-op dispatch would dominate);
- the decode loop is ONE compiled ``lax.scan`` over one-token steps with
  on-device sampling — one dispatch per generate() call, KV caches donated;
- configs without cache support (pipeline stages, MoE layers) fall back to
  full-prefix recompute, which is also the greedy-decoding oracle.

Host model contract: ``self.model.init_cache(b, total, dtype=None)``
(``dtype="int8"`` must yield quantized 4-tuple caches or raise); cached
forward
``self.model(ids, caches=..., seq_lens=...) -> (hidden, caches)``;
``self.logits(hidden)``; ``self._cache_supported()``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _is_int8(dtype) -> bool:
    """Normalize every spelling of int8 — "int8", "paddle.int8", np.int8,
    jnp.int8 — so none silently allocates raw UNSCALED int8 caches."""
    if dtype is None:
        return False
    if str(dtype) in ("int8", "paddle.int8"):
        return True
    try:
        import numpy as _np
        return _np.dtype(dtype) == _np.int8
    except TypeError:
        return False


def make_dense_caches(n_layers, batch, max_len, kv_heads, head_dim, dtype):
    """Per-layer dense (k, v) cache pairs (shared by the model families).

    ``dtype="int8"`` allocates QUANTIZED caches: 4-tuples
    ``(k_int8, v_int8, k_scale, v_scale)`` with per-(position, head)
    f32 scales — decode is HBM-bandwidth-bound (docs/BENCH.md "Decode
    throughput"), so halving the cache bytes is the lever that matters."""
    shape = (batch, max_len, kv_heads, head_dim)
    if _is_int8(dtype):
        sshape = (batch, max_len, kv_heads)
        return [(jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                 jnp.ones(sshape, jnp.float32),
                 jnp.ones(sshape, jnp.float32))
                for _ in range(n_layers)]
    dtype = jnp.dtype(dtype)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(n_layers)]


def run_cached_layers(layers, x, caches, call):
    """Thread (x, per-layer cache) through the decoder stack, unwrapping
    RecomputeWrapper (remat is pointless for cached inference)."""
    from ..distributed.recompute import RecomputeWrapper
    layers = list(layers)
    if len(layers) != len(caches):
        raise ValueError(
            f"cache list has {len(caches)} entries for {len(layers)} "
            f"decoder layers — was it built by a different config?")
    new_caches = []
    for layer, cache in zip(layers, caches):
        inner = layer.inner if isinstance(layer, RecomputeWrapper) else layer
        x, cache = call(inner, x, cache)
        new_caches.append(cache)
    return x, new_caches


def filter_logits(lg, top_k: int = 0, top_p: float = 1.0,
                  repetition_penalty: float = 1.0, seen=None,
                  temperature: float = 1.0):
    """Decode-strategy logit transforms (reference:
    paddle generation's TopKProcess/TopPProcess/repetition penalty),
    trace-safe so they run inside the compiled decode scan.  Reference
    order: penalty on raw logits → temperature → top-k → top-p (the
    nucleus is computed on the TEMPERATURE-SCALED distribution — at
    temperature≠1 the kept set differs from the unscaled one).

    ``seen``: (b, vocab) count of already-emitted tokens (prompt included)
    for the repetition penalty; pass None to skip.  The returned logits
    are already temperature-scaled: sample them directly."""
    if repetition_penalty != 1.0 and seen is not None:
        pen = jnp.where(lg > 0, lg / repetition_penalty,
                        lg * repetition_penalty)
        lg = jnp.where(seen > 0, pen, lg)
    if temperature > 0 and temperature != 1.0:
        lg = lg / temperature
    if (top_k and top_k > 0) or top_p < 1.0:
        # one descending sort serves both filters (this runs per decoded
        # token inside the compiled scan — no second O(V log V) pass)
        srt = jnp.sort(lg, axis=-1)[..., ::-1]
        if top_k and top_k > 0:
            kth = srt[..., int(top_k) - 1][..., None]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
            # reference order: TopP sees the TopK-filtered distribution
            srt = jnp.where(jnp.arange(srt.shape[-1]) < int(top_k), srt,
                            -jnp.inf)
        if top_p < 1.0:
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep = (cum - probs) < top_p       # always keeps the top token
            kth = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                          keepdims=True)
            lg = jnp.where(lg < kth, -jnp.inf, lg)
    return lg


def _seen_counts(ids, vocab_size):
    b = ids.shape[0]
    seen = jnp.zeros((b, vocab_size), jnp.int32)
    return seen.at[jnp.arange(b)[:, None], ids].add(1)


class CachedGenerationMixin:
    def _cache_supported(self) -> bool:
        return False  # families opt in

    def _sample(self, logits, temperature, top_k=0, top_p=1.0,
                repetition_penalty=1.0, seen=None):
        logits = filter_logits(logits, top_k, top_p, repetition_penalty,
                               seen, temperature)
        if temperature > 0:
            from ..core import random as prandom
            return jax.random.categorical(prandom.next_key("gen"),
                                          logits, axis=-1)
        return jnp.argmax(logits, axis=-1)

    def _decode_loop_fn(self, n_steps: int, temperature: float,
                        top_k: int = 0, top_p: float = 1.0,
                        repetition_penalty: float = 1.0,
                        eos_token_id=None, pad_token_id=None):
        """Whole decode loop as ONE compiled program (lax.scan). Single-slot
        memo: varying max_new_tokens/temperature/strategy must not
        accumulate one XLA executable per combination.

        EOS semantics (reference generate): a row that has emitted
        ``eos_token_id`` keeps emitting ``pad_token_id`` (default: the eos
        id) — the scan stays fixed-length, finished rows are frozen."""
        cached_key, fn = self.__dict__.get("_decode_loop_memo", (None, None))
        track_seen = repetition_penalty != 1.0
        pad = pad_token_id if pad_token_id is not None else eos_token_id
        # key on the RESOLVED pad: pad_token_id=None vs pad==eos trace the
        # same program and must share the memo slot
        key = (n_steps, temperature, top_k, top_p, repetition_penalty,
               eos_token_id, pad)
        if cached_key != key:
            fn = None
        if fn is None:
            from ..nn.layer import _swapped_params, functional_call

            def one_step(params, tok, caches, lens, rng, i, seen):
                mp = {k[len("model."):]: v for k, v in params.items()
                      if k.startswith("model.")}
                hidden, caches = functional_call(
                    self.model, mp, tok[:, None], caches=caches,
                    seq_lens=lens, training=False)
                with _swapped_params(self, params):
                    lg = self.logits(hidden[:, -1:])[:, 0]
                lg = filter_logits(lg, top_k, top_p, repetition_penalty,
                                   seen, temperature)
                if temperature > 0:
                    nxt = jax.random.categorical(
                        jax.random.fold_in(rng, i), lg, axis=-1)
                else:
                    nxt = jnp.argmax(lg, axis=-1)
                return nxt.astype(tok.dtype), caches

            def loop(params, tok0, caches, lens0, rng, seen0, done0):
                def body(carry, i):
                    tok, caches, lens, seen, done = carry
                    nxt, caches = one_step(params, tok, caches, lens, rng,
                                           i, seen)
                    if eos_token_id is not None:
                        nxt = jnp.where(done, jnp.asarray(pad, nxt.dtype),
                                        nxt)
                        done = done | (nxt == eos_token_id)
                    if track_seen:
                        seen = seen.at[jnp.arange(seen.shape[0]),
                                       nxt].add(1)
                    return (nxt, caches, lens + 1, seen, done), nxt

                (_, caches, _, _, _), toks = jax.lax.scan(
                    body, (tok0, caches, lens0, seen0, done0),
                    jnp.arange(n_steps))
                return jnp.swapaxes(toks, 0, 1), caches   # (b, n_steps)

            fn = jax.jit(loop, donate_argnums=(2,))
            self.__dict__["_decode_loop_memo"] = (key, fn)
        return fn

    def _beam_loop_fn(self, n_steps: int, num_beams: int,
                      temperature: float = 0.0,
                      repetition_penalty: float = 1.0,
                      eos_token_id=None, pad_token_id=None):
        """Whole beam-search decode as ONE compiled lax.scan (reference:
        generation BeamSearchDecoder). Beams ride the batch dim (b·nb);
        each step reorders caches, histories and penalty counts by the
        surviving beams' parent indices. Fixed length — no EOS early-exit
        (XLA static shapes; the reference pads to max length too)."""
        cached_key, fn = self.__dict__.get("_beam_loop_memo", (None, None))
        pad = pad_token_id if pad_token_id is not None else eos_token_id
        key = (n_steps, num_beams, temperature, repetition_penalty,
               eos_token_id, pad)
        if cached_key != key:
            fn = None
        if fn is None:
            from ..nn.layer import _swapped_params, functional_call
            nb = num_beams

            def loop(params, tok0, caches, lens0, scores0, seen0, done0):
                b = scores0.shape[0]
                hist0 = jnp.zeros((b, nb, n_steps + 1), tok0.dtype)
                hist0 = hist0.at[:, :, 0].set(tok0.reshape(b, nb))

                def body(carry, i):
                    tok, caches, lens, scores, hist, seen, done = carry
                    mp = {k[len("model."):]: v for k, v in params.items()
                          if k.startswith("model.")}
                    hidden, caches = functional_call(
                        self.model, mp, tok[:, None], caches=caches,
                        seq_lens=lens, training=False)
                    with _swapped_params(self, params):
                        lg = self.logits(hidden[:, -1:])[:, 0]
                    lg = filter_logits(
                        lg.astype(jnp.float32),
                        repetition_penalty=repetition_penalty, seen=seen,
                        temperature=temperature if temperature > 0 else 1.0)
                    logp = jax.nn.log_softmax(lg)
                    vocab = logp.shape[-1]
                    if eos_token_id is not None:
                        # frozen beams extend only by pad at zero cost, so
                        # they compete in top-k by their FINAL score
                        pad_row = jnp.full((vocab,), -jnp.inf,
                                           logp.dtype).at[pad].set(0.0)
                        logp = jnp.where(done[:, None], pad_row[None],
                                         logp)
                    total = scores[:, :, None] + logp.reshape(b, nb, vocab)
                    top_v, top_i = jax.lax.top_k(
                        total.reshape(b, nb * vocab), nb)
                    parent = top_i // vocab             # (b, nb)
                    nxt = (top_i % vocab).astype(tok.dtype)
                    flat_parent = (jnp.arange(b)[:, None] * nb
                                   + parent).reshape(-1)
                    caches = jax.tree.map(lambda c: c[flat_parent], caches)
                    hist = hist[jnp.arange(b)[:, None], parent]
                    hist = hist.at[:, :, i + 1].set(nxt)
                    if repetition_penalty != 1.0:
                        seen = seen[flat_parent].at[
                            jnp.arange(b * nb), nxt.reshape(-1)].add(1)
                    if eos_token_id is not None:
                        done = done[flat_parent] | \
                            (nxt.reshape(-1) == eos_token_id)
                    return (nxt.reshape(-1), caches, lens + 1, top_v,
                            hist, seen, done), None

                (tokN, caches, _, scores, hist, _, _), _ = jax.lax.scan(
                    body,
                    (tok0, caches, lens0, scores0, hist0, seen0, done0),
                    jnp.arange(n_steps))
                return hist, scores

            fn = jax.jit(loop, donate_argnums=(2,))
            self.__dict__["_beam_loop_memo"] = (key, fn)
        return fn

    def _prefill_fn(self):
        """Jitted prompt prefill (eager per-op dispatch of a whole forward
        would dominate generate() latency); memoized per model."""
        prefill = self.__dict__.get("_prefill_compiled")
        if prefill is None:
            from ..nn.layer import _swapped_params, functional_call

            def _prefill(params, input_ids, caches):
                mp = {k[len("model."):]: v for k, v in params.items()
                      if k.startswith("model.")}
                hidden, caches = functional_call(
                    self.model, mp, input_ids, caches=caches,
                    training=False)
                with _swapped_params(self, params):
                    lg = self.logits(hidden[:, -1:])[:, 0]
                return lg, caches

            prefill = jax.jit(_prefill, donate_argnums=(2,))
            self.__dict__["_prefill_compiled"] = prefill
        return prefill

    def _beam_search(self, input_ids, max_new_tokens, num_beams, total,
                     temperature=0.0, repetition_penalty=1.0,
                     eos_token_id=None, pad_token_id=None,
                     kv_cache_dtype=None):
        from ..nn.layer import serving_params
        b, prompt_len = input_ids.shape
        nb = num_beams
        params = serving_params(self)
        prefill = self._prefill_fn()
        # prefill ONCE at batch b (the dominant FLOP cost for long
        # prompts), then repeat the caches across beams — the rows are
        # byte-identical, so nb separate prefills would be pure waste
        caches = self.model.init_cache(b, total, dtype=kv_cache_dtype)
        logits, caches = prefill(params, input_ids, caches)
        caches = jax.tree.map(lambda c: jnp.repeat(c, nb, axis=0), caches)
        logits = jnp.repeat(logits, nb, axis=0)          # (b·nb, V)
        vocab_size = logits.shape[-1]
        track = repetition_penalty != 1.0
        seen = (_seen_counts(jnp.repeat(input_ids, nb, axis=0), vocab_size)
                if track else jnp.zeros((b * nb, 1), jnp.int32))
        logits = filter_logits(
            logits.astype(jnp.float32),
            repetition_penalty=repetition_penalty,
            seen=seen if track else None,
            temperature=temperature if temperature > 0 else 1.0)
        logp = jax.nn.log_softmax(logits)
        # seed: only beam 0 is live, and its first expansion takes the
        # top-nb distinct tokens (the standard first-step trick)
        first_v, first_tok = jax.lax.top_k(
            logp.reshape(b, nb, vocab_size)[:, 0], nb)
        scores = first_v                                  # (b, nb)
        tok0 = first_tok.astype(input_ids.dtype).reshape(-1)
        if track:
            seen = seen.at[jnp.arange(b * nb), tok0].add(1)
        if max_new_tokens == 1:
            best = jnp.argmax(scores, axis=1)
            picked = first_tok[jnp.arange(b), best][:, None]
            return jnp.concatenate(
                [input_ids, picked.astype(input_ids.dtype)], axis=1)
        loop = self._beam_loop_fn(max_new_tokens - 1, nb,
                                  float(temperature),
                                  float(repetition_penalty),
                                  eos_token_id, pad_token_id)
        lens = jnp.full((b * nb,), prompt_len, jnp.int32)
        done0 = (tok0 == eos_token_id) if eos_token_id is not None else \
            jnp.zeros((b * nb,), bool)
        hist, scores = loop(params, tok0, caches, lens, scores, seen,
                            done0)
        best = jnp.argmax(scores, axis=1)                 # (b,)
        toks = hist[jnp.arange(b), best]                  # (b, n_steps+1)
        return jnp.concatenate([input_ids, toks.astype(input_ids.dtype)],
                               axis=1)

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 use_cache=True, max_len=None, top_k=0, top_p=1.0,
                 repetition_penalty=1.0, decode_strategy=None,
                 num_beams=1, eos_token_id=None, pad_token_id=None,
                 kv_cache_dtype=None):
        """Autoregressive generation. ``use_cache=True`` (default) prefills
        the dense KV caches once, then runs the WHOLE decode loop as one
        compiled ``lax.scan`` (one dispatch per call). ``use_cache=False``
        recomputes the full prefix each step; under GREEDY decoding
        (temperature=0) the two paths are token-identical — with
        temperature>0 they draw from different RNG stream shapes and
        legitimately sample different tokens. Falls back to recompute for
        configs without cache support (pipeline stages, MoE layers).

        ``top_k``/``top_p``/``repetition_penalty`` follow the reference
        generate() semantics (TopKProcess/TopPProcess; penalty counts the
        prompt too). ``decode_strategy`` is the reference's name for the
        mode: "greedy_search" forces temperature 0, "sampling" requires
        temperature > 0; "beam_search" (or num_beams > 1) runs the
        compiled beam decoder.

        ``kv_cache_dtype="int8"`` quantizes the KV caches (per-position,
        per-head symmetric scales) — decode is HBM-bandwidth-bound, so
        this speeds up cache-dominated operating points (large
        batch·context; docs/BENCH.md "int8 KV cache") at a small accuracy
        cost.  It requires the cached path (errors on recompute
        fallback).

        ``eos_token_id``: a row that emits it keeps emitting
        ``pad_token_id`` (default: the eos id) for the remaining steps —
        output length stays fixed (XLA static shapes; the reference pads
        batch generation to max length the same way). In beam search a
        finished beam is frozen: it extends only by pad at zero cost, so
        it competes in the final ranking by its score at EOS."""
        if decode_strategy not in (None, "greedy_search", "sampling",
                                   "beam_search"):
            raise ValueError(
                f"unsupported decode_strategy {decode_strategy!r}")
        if num_beams > 1:
            if decode_strategy is None:       # reference: beams imply beam search
                decode_strategy = "beam_search"
            elif decode_strategy != "beam_search":
                raise ValueError(
                    f"num_beams={num_beams} requires "
                    f"decode_strategy='beam_search', got {decode_strategy!r}")
        # shared cache-capacity contract for every cached strategy
        prompt_len = input_ids.shape[1]
        total = max_len if max_len is not None else \
            (prompt_len + max_new_tokens)
        if total < prompt_len + max_new_tokens:
            raise ValueError(
                f"max_len={total} < prompt ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}): the cache would silently drop keys")
        if decode_strategy == "beam_search":
            if num_beams <= 1:
                raise ValueError(
                    "beam_search needs num_beams > 1 (reference semantics; "
                    "num_beams=1 IS greedy_search)")
            if top_k or top_p < 1.0:
                raise NotImplementedError(
                    "top_k/top_p do not apply to deterministic beam "
                    "search — use decode_strategy='sampling'")
            if not (use_cache and self._cache_supported()):
                raise NotImplementedError(
                    "beam_search needs the KV-cache path (this config "
                    "falls back to recompute)")
            if max_new_tokens <= 0:
                return input_ids
            return self._beam_search(input_ids, max_new_tokens, num_beams,
                                     total, temperature, repetition_penalty,
                                     eos_token_id, pad_token_id,
                                     kv_cache_dtype=kv_cache_dtype)
        if decode_strategy == "greedy_search":
            temperature = 0.0
        elif decode_strategy == "sampling" and temperature <= 0:
            temperature = 1.0
        if max_new_tokens <= 0:
            return input_ids
        vocab = getattr(self.cfg, "vocab_size", None)
        track_seen = repetition_penalty != 1.0 and vocab is not None
        pad_id = pad_token_id if pad_token_id is not None else eos_token_id
        if not (use_cache and self._cache_supported()):
            if kv_cache_dtype is not None:
                # silent full-precision recompute would let the caller
                # believe they validated a quantized cache
                raise ValueError(
                    "kv_cache_dtype set but this call uses the recompute "
                    "path (use_cache=False or no cache support) — there "
                    "is no cache to quantize")
            ids = input_ids
            # counts built once from the prompt, then updated per token
            # (rebuilding the (b, vocab) matrix per step would be
            # O(steps·b·vocab))
            seen = _seen_counts(ids, vocab) if track_seen else None
            bidx = jnp.arange(ids.shape[0])
            done = jnp.zeros((ids.shape[0],), bool)
            for _ in range(max_new_tokens):
                logits = self(ids)[:, -1]
                nxt = self._sample(logits, temperature, top_k, top_p,
                                   repetition_penalty, seen)
                if eos_token_id is not None:
                    nxt = jnp.where(done, jnp.asarray(pad_id, nxt.dtype),
                                    nxt)
                    done = done | (nxt == eos_token_id)
                if seen is not None:
                    seen = seen.at[bidx, nxt].add(1)
                ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
            return ids

        from ..nn.layer import serving_params
        b = input_ids.shape[0]       # total/prompt_len validated above
        params = serving_params(self)
        prefill = self._prefill_fn()
        caches = self.model.init_cache(b, total, dtype=kv_cache_dtype)
        logits, caches = prefill(params, input_ids, caches)
        seen = _seen_counts(input_ids, vocab) if track_seen else None
        tok = self._sample(logits, temperature, top_k, top_p,
                           repetition_penalty, seen).astype(input_ids.dtype)
        if max_new_tokens == 1:
            return jnp.concatenate([input_ids, tok[:, None]], axis=1)

        from ..core import random as prandom
        rng = prandom.next_key("gen") if temperature > 0 else \
            jax.random.key(0)
        loop = self._decode_loop_fn(max_new_tokens - 1, float(temperature),
                                    int(top_k), float(top_p),
                                    float(repetition_penalty),
                                    eos_token_id, pad_token_id)
        lens = jnp.full((b,), prompt_len, jnp.int32)
        if seen is not None:
            seen = seen.at[jnp.arange(b), tok].add(1)
        else:
            # fixed carry structure: a 1-wide dummy when penalty is off
            seen = jnp.zeros((b, 1), jnp.int32)
        done = (tok == eos_token_id) if eos_token_id is not None else \
            jnp.zeros((b,), bool)
        toks, _ = loop(params, tok, caches, lens, rng, seen, done)
        return jnp.concatenate([input_ids, tok[:, None], toks], axis=1)
