"""T5 encoder-decoder family (PaddleNLP ``T5ForConditionalGeneration``
scope).

Reference capability: PaddleNLP paddlenlp/transformers/t5/modeling.py
(the ecosystem's seq2seq workhorse; SURVEY §0 scope note). Module names
mirror the HF layout (``encoder.block.N.layer.0.SelfAttention.q`` …) so
``models.hf.from_hf`` imports HF T5 checkpoints by pure transpose, and
the torch-oracle parity test pins the architecture.

T5-specific numerics kept exactly: no 1/sqrt(d) attention scale, shared
bucketed relative-position bias held by block 0 of each stack, RMS-style
T5LayerNorm in fp32, and the d_model**-0.5 output scale when embeddings
are tied.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers_common import Embedding, LayerList, Linear

__all__ = ["T5Config", "T5Model", "t5"]


@dataclasses.dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    tie_word_embeddings: bool = True
    decoder_start_token_id: int = 0


PRESETS = {
    "tiny": T5Config(vocab_size=128, d_model=64, d_kv=16, d_ff=128,
                     num_layers=2, num_decoder_layers=2, num_heads=4),
    "t5-small": T5Config(),
    "t5-base": T5Config(d_model=768, d_ff=3072, num_layers=12,
                        num_decoder_layers=12, num_heads=12),
}


class _T5LayerNorm(Layer):
    """RMS norm, no bias/mean-centering (HF T5LayerNorm semantics)."""

    def __init__(self, d, eps):
        super().__init__()
        self.weight = self.create_parameter((d,))
        self.weight = jnp.ones((d,), jnp.float32)
        self.eps = eps

    def forward(self, x):
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        return (xf * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) \
            * self.weight.astype(x.dtype)


def _relative_position_bucket(rel, bidirectional, num_buckets, max_distance):
    """Exact HF bucketing (modeling_t5._relative_position_bucket)."""
    ret = 0
    n = -rel
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-9)
        / jnp.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


class _T5Attention(Layer):
    def __init__(self, cfg: T5Config, has_relative_bias: bool,
                 bidirectional: bool):
        super().__init__()
        inner = cfg.num_heads * cfg.d_kv
        self.q = Linear(cfg.d_model, inner, bias_attr=False)
        self.k = Linear(cfg.d_model, inner, bias_attr=False)
        self.v = Linear(cfg.d_model, inner, bias_attr=False)
        self.o = Linear(inner, cfg.d_model, bias_attr=False)
        self.nh, self.dkv = cfg.num_heads, cfg.d_kv
        self.cfg = cfg
        self.bidirectional = bidirectional
        if has_relative_bias:
            self.relative_attention_bias = Embedding(
                cfg.relative_attention_num_buckets, cfg.num_heads)

    def compute_bias(self, qlen, klen):
        ctx = jnp.arange(qlen)[:, None]
        mem = jnp.arange(klen)[None, :]
        buckets = _relative_position_bucket(
            mem - ctx, self.bidirectional,
            self.cfg.relative_attention_num_buckets,
            self.cfg.relative_attention_max_distance)
        vals = self.relative_attention_bias(buckets)      # [q, k, H]
        return jnp.transpose(vals, (2, 0, 1))[None]       # [1, H, q, k]

    def forward(self, x, kv=None, position_bias=None, mask=None):
        b, sq = x.shape[:2]
        kv = x if kv is None else kv
        sk = kv.shape[1]
        q = self.q(x).reshape(b, sq, self.nh, self.dkv)
        k = self.k(kv).reshape(b, sk, self.nh, self.dkv)
        v = self.v(kv).reshape(b, sk, self.nh, self.dkv)
        # T5: NO 1/sqrt(d) scale; bias added to raw logits
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        if position_bias is not None:
            logits = logits + position_bias
        if mask is not None:
            logits = logits + mask
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return self.o(out.reshape(b, sq, self.nh * self.dkv))


class _T5FF(Layer):
    def __init__(self, cfg: T5Config):
        super().__init__()
        self.wi = Linear(cfg.d_model, cfg.d_ff, bias_attr=False)
        self.wo = Linear(cfg.d_ff, cfg.d_model, bias_attr=False)

    def forward(self, x):
        return self.wo(F.relu(self.wi(x)))


class _SelfLayer(Layer):
    def __init__(self, cfg, has_bias, bidirectional):
        super().__init__()
        self.SelfAttention = _T5Attention(cfg, has_bias, bidirectional)
        self.layer_norm = _T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon)

    def forward(self, x, position_bias=None, mask=None):
        return x + self.SelfAttention(self.layer_norm(x),
                                      position_bias=position_bias, mask=mask)


class _CrossLayer(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.EncDecAttention = _T5Attention(cfg, False, True)
        self.layer_norm = _T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon)

    def forward(self, x, enc, mask=None):
        return x + self.EncDecAttention(self.layer_norm(x), kv=enc, mask=mask)


class _FFLayer(Layer):
    def __init__(self, cfg):
        super().__init__()
        self.DenseReluDense = _T5FF(cfg)
        self.layer_norm = _T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon)

    def forward(self, x):
        return x + self.DenseReluDense(self.layer_norm(x))


class _Block(Layer):
    def __init__(self, cfg, has_bias, is_decoder):
        super().__init__()
        layers = [_SelfLayer(cfg, has_bias, bidirectional=not is_decoder)]
        if is_decoder:
            layers.append(_CrossLayer(cfg))
        layers.append(_FFLayer(cfg))
        self.layer = LayerList(layers)
        self.is_decoder = is_decoder

    def forward(self, x, enc=None, position_bias=None, self_mask=None,
                cross_mask=None):
        x = self.layer[0](x, position_bias, self_mask)
        if self.is_decoder:
            x = self.layer[1](x, enc, cross_mask)
        return self.layer[-1](x)


class _Stack(Layer):
    def __init__(self, cfg, n_layers, is_decoder):
        super().__init__()
        self.block = LayerList([_Block(cfg, has_bias=(i == 0),
                                       is_decoder=is_decoder)
                                for i in range(n_layers)])
        self.final_layer_norm = _T5LayerNorm(cfg.d_model,
                                             cfg.layer_norm_epsilon)
        self.is_decoder = is_decoder

    def forward(self, x, enc=None, self_mask=None, cross_mask=None):
        sq = x.shape[1]
        bias = self.block[0].layer[0].SelfAttention.compute_bias(sq, sq)
        for blk in self.block:
            x = blk(x, enc, bias, self_mask, cross_mask)
        return self.final_layer_norm(x)


class T5Model(Layer):
    """Conditional generation model (HF T5ForConditionalGeneration
    layout): forward(input_ids, decoder_input_ids) → logits."""

    def __init__(self, cfg: T5Config):
        super().__init__()
        self.cfg = cfg
        self.shared = Embedding(cfg.vocab_size, cfg.d_model)
        self.encoder = _Stack(cfg, cfg.num_layers, is_decoder=False)
        self.decoder = _Stack(cfg, cfg.num_decoder_layers, is_decoder=True)
        if not cfg.tie_word_embeddings:
            self.lm_head = Linear(cfg.d_model, cfg.vocab_size,
                                  bias_attr=False)

    def forward(self, input_ids, decoder_input_ids, attention_mask=None,
                labels=None):
        cfg = self.cfg
        enc_mask = None
        if attention_mask is not None:
            enc_mask = (1.0 - attention_mask[:, None, None, :].astype(
                jnp.float32)) * -1e9
        enc = self.encoder(self.shared(input_ids), self_mask=enc_mask)
        sq = decoder_input_ids.shape[1]
        causal = jnp.where(
            jnp.tril(jnp.ones((sq, sq), bool))[None, None], 0.0, -1e9)
        dec = self.decoder(self.shared(decoder_input_ids), enc,
                           self_mask=causal, cross_mask=enc_mask)
        if cfg.tie_word_embeddings:
            dec = dec * (cfg.d_model ** -0.5)
            logits = dec @ self.shared.weight.T
        else:
            logits = self.lm_head(dec)
        if labels is None:
            return logits
        loss = F.cross_entropy(logits.astype(jnp.float32).reshape(
            -1, cfg.vocab_size), labels.reshape(-1), reduction="none")
        valid = (labels.reshape(-1) != -100)
        return jnp.sum(loss * valid) / jnp.maximum(jnp.sum(valid), 1)


def t5(name_or_config="tiny", **overrides) -> T5Model:
    cfg = (PRESETS[name_or_config] if isinstance(name_or_config, str)
           else name_or_config)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return T5Model(cfg)
