"""HuggingFace checkpoint import.

Reference capability: PaddleNLP's ``from_pretrained`` conversion of HF
torch checkpoints into paddle weights (PaddleNLP
paddlenlp/transformers/llama/modeling.py name-mapping tables; SURVEY §0
scope note — the model zoo lives in sibling repos).

Our module names already mirror HF (``model.layers.N.self_attn.q_proj``),
so conversion is: (a) transpose 2-D linear kernels — torch ``nn.Linear``
stores ``[out, in]``, this framework (paddle convention) stores
``[in, out]``; (b) keep embeddings/norms as-is. Works straight from a
``transformers`` model object, a torch ``state_dict``, or a dict of
numpy arrays — no torch required for the numpy path.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

__all__ = ["load_hf_state_dict", "from_hf"]

# parameters that keep their layout (everything else 2-D is a linear
# kernel and gets transposed)
_NO_TRANSPOSE_SUFFIXES = (
    "embed_tokens.weight",      # [vocab, hidden] on both sides
    "input_layernorm.weight",
    "post_attention_layernorm.weight",
    "norm.weight",
    # BERT/ERNIE embeddings (2-D lookup tables, not kernels)
    "word_embeddings.weight",
    "position_embeddings.weight",
    "token_type_embeddings.weight",
    "task_type_embeddings.weight",
    # T5: shared embedding + relative-bias table
    "shared.weight",
    "relative_attention_bias.weight",
)


def _to_numpy(v) -> np.ndarray:
    if isinstance(v, np.ndarray):
        return v
    # torch tensor (incl. bf16) without importing torch at module scope
    if hasattr(v, "detach"):
        t = v.detach().cpu()
        if str(t.dtype) == "torch.bfloat16":
            t = t.float()
        return t.numpy()
    return np.asarray(v)


def load_hf_state_dict(hf_state: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """HF llama/mixtral-style state_dict → this framework's state_dict."""
    import re

    out = {}
    experts: Dict[str, Dict[int, np.ndarray]] = {}
    has_shared = any(k == "shared.weight" for k in hf_state)
    for name, val in hf_state.items():
        arr = _to_numpy(val)
        if name.endswith("rotary_emb.inv_freq"):
            continue  # recomputed, never a parameter here
        if has_shared and name.endswith("embed_tokens.weight"):
            continue  # T5 duplicates of shared.weight
        m = re.match(r"(.*block_sparse_moe)\.experts\.(\d+)\.(w[123])\.weight$",
                     name)
        if m:
            # Mixtral per-expert w1(gate)/w3(up)/w2(down) [out,in] →
            # stacked batched kernels [E, in, out]
            prefix, eid, w = m.group(1), int(m.group(2)), m.group(3)
            ours = {"w1": "gate_proj__weight", "w3": "up_proj__weight",
                    "w2": "down_proj__weight"}[w]
            experts.setdefault(f"{prefix}.{ours}", {})[eid] = arr.T
            continue
        if arr.ndim == 2 and not name.endswith(_NO_TRANSPOSE_SUFFIXES):
            arr = arr.T
        out[name] = arr
    for key, by_id in experts.items():
        out[key] = np.stack([by_id[i] for i in range(len(by_id))])
    return out


_GPT2_RENAMES = (
    ("transformer.wte.", "model.embed_tokens."),
    ("transformer.wpe.", "model.embed_positions."),
    ("transformer.ln_f.", "model.ln_f."),
    ("transformer.h.", "model.h."),
    (".attn.c_attn.", ".attn.qkv_proj."),
    (".attn.c_proj.", ".attn.out_proj."),
    (".mlp.c_fc.", ".mlp.fc_in."),
    (".mlp.c_proj.", ".mlp.fc_out."),
)


def load_gpt2_state_dict(hf_state: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """HF GPT-2 state_dict → this framework's GPT state_dict.

    GPT-2's ``Conv1D`` already stores kernels ``[in, out]`` (unlike
    ``nn.Linear``), so this is pure renaming — fused c_attn maps onto our
    fused qkv_proj directly. The causal-mask buffers (``attn.bias``,
    ``attn.masked_bias``) and the tied ``lm_head.weight`` are dropped.
    """
    out = {}
    for name, val in hf_state.items():
        if (name.endswith("attn.bias") and _to_numpy(val).ndim != 1) or \
                name.endswith("attn.masked_bias") or name == "lm_head.weight":
            continue
        for old, new in _GPT2_RENAMES:
            name = name.replace(old, new)
        out[name] = _to_numpy(val)
    return out


def from_hf(model, hf_model_or_state) -> None:
    """Load a transformers model (or its state_dict) into ``model``.

    >>> hf = transformers.LlamaForCausalLM(cfg)
    >>> net = llama(matching_cfg)
    >>> from_hf(net, hf)
    """
    state = (hf_model_or_state.state_dict()
             if hasattr(hf_model_or_state, "state_dict")
             else hf_model_or_state)
    if any(k.startswith("transformer.wte") for k in state):
        converted = load_gpt2_state_dict(state)
    else:
        converted = load_hf_state_dict(state)
    ours = model.state_dict()
    if "lm_head.weight" in converted and "lm_head.weight" not in ours:
        # tied-embedding models (T5 etc.) export a duplicate head
        converted.pop("lm_head.weight")
    missing = [k for k in ours if k not in converted]
    unexpected = [k for k in converted if k not in ours]
    if missing or unexpected:
        raise ValueError(
            f"HF conversion mismatch — missing: {missing[:5]} "
            f"unexpected: {unexpected[:5]} "
            f"({len(missing)}/{len(unexpected)} total)")
    for k, v in converted.items():
        if tuple(v.shape) != tuple(ours[k].shape):
            raise ValueError(
                f"{k}: converted shape {v.shape} != model {ours[k].shape}")
    model.set_state_dict(converted)
