"""Llama model family (flagship; BASELINE.json configs[0]/[4]).

Reference capability: PaddleNLP's LlamaForCausalLM expressed with the core
framework's fleet layers (the reference core provides the layers; the model
zoo lives in PaddleNLP — SURVEY.md §0 scope note).  Built here TPU-first:

- tensor parallel via ColumnParallel/RowParallel/VocabParallelEmbedding
  partition specs ("mp" axis), degrading to serial when mp=1;
- Megatron-SP sequence sharding of norm/residual activations (sep §5.7-2);
- GQA + RoPE + flash-attention dispatch (Pallas kernel on TPU);
- optional per-layer rematerialisation;
- everything jit-compiles into one XLA program via TrainStep.
"""

from __future__ import annotations

import dataclasses
import math
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..distributed.mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                                     RowParallelLinear, VocabParallelEmbedding,
                                     constrain)
from ..distributed.recompute import RecomputeWrapper
from .generation import CachedGenerationMixin


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    use_recompute: bool = False
    recompute_policy: Optional[str] = None  # full recompute; "dots" saves s×s attn probs = OOM at long seq
    recompute_num_layers: Optional[int] = None  # Megatron-style partial remat: only the first N layers
    sequence_parallel: bool = False
    context_parallel: Optional[str] = None  # None | "ring" | "ulysses" (sep axis)
    pipeline_stages: int = 1        # >1: stacked pp-sharded decoder body
    num_microbatches: Optional[int] = None  # default: pipeline_stages
    virtual_pp_degree: int = 1      # interleaved-schedule chunks per stage
    loss_seq_chunks: int = 1        # >1: rematerialized seq-chunked vocab CE
    fuse_qkv_mlp: bool = False      # trace-time concat of qkv / gate+up kernels
    # fused-kernel library (docs/KERNELS.md): "on" routes norm+rope+qkv
    # and the swiglu MLP through incubate's fused entry points (Pallas
    # kernels on TPU, the equivalent XLA composition elsewhere); "mega"
    # is "on" plus the decode megakernel — the whole decoder-layer
    # attention block (norm→qkv→rope→ragged attention→o_proj+residual)
    # as ONE dispatch on the ragged serving step
    # (ops/pallas/mega_decode.py; XLA composition off-TPU and wherever
    # its supported() gate declines); "auto" fuses only where a kernel
    # will actually serve (TPU, no mesh, not vetoed by
    # tools/tuned_configs.json) so CPU behavior is unchanged; "off"
    # keeps the unfused projections.  Takes precedence over
    # fuse_qkv_mlp where both apply.
    fused_ops: str = "auto"
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    def num_params(self) -> int:
        h, i, v, l = (self.hidden_size, self.intermediate_size,
                      self.vocab_size, self.num_hidden_layers)
        kvh = self.num_key_value_heads * self.head_dim
        per_layer = h * h + 2 * h * kvh + h * h + 3 * h * i + 2 * h
        embed = v * h * (1 if self.tie_word_embeddings else 2)
        return l * per_layer + embed + h


PRESETS = {
    "llama2-7b": LlamaConfig(),
    "llama2-13b": LlamaConfig(hidden_size=5120, intermediate_size=13824,
                              num_hidden_layers=40, num_attention_heads=40,
                              num_key_value_heads=40),
    "llama2-70b": LlamaConfig(hidden_size=8192, intermediate_size=28672,
                              num_hidden_layers=80, num_attention_heads=64,
                              num_key_value_heads=8),
    "llama-1b": LlamaConfig(hidden_size=2048, intermediate_size=5504,
                            num_hidden_layers=16, num_attention_heads=16,
                            num_key_value_heads=16, vocab_size=32000),
    "llama-350m": LlamaConfig(hidden_size=1024, intermediate_size=2816,
                              num_hidden_layers=24, num_attention_heads=16,
                              num_key_value_heads=16),
    # same parameter count as llama-350m but 8 heads of head_dim 128 — the
    # north-star's (Llama-2-7B) attention geometry, where qk/sv matmuls
    # fill the 128-wide MXU instead of running K/N=64 at half occupancy
    "llama-350m-hd128": LlamaConfig(hidden_size=1024, intermediate_size=2816,
                                    num_hidden_layers=24,
                                    num_attention_heads=8,
                                    num_key_value_heads=8),
    "tiny": LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=2, num_attention_heads=4,
                        num_key_value_heads=2, max_position_embeddings=128),
}


def _weight_attr(cfg: LlamaConfig):
    # reference Llama init: Normal(0, initializer_range) on every projection
    from ..nn.layer import ParamAttr
    return ParamAttr(initializer=I.Normal(0.0, cfg.initializer_range))


def _use_fused(cfg, op: str, key=None, probe=None, layers=()) -> bool:
    """Trace-time fused-op resolution (ops.tuning owns the policy).

    Sequence-parallel keeps the unfused path (the fused entry points
    bypass the Column/RowParallel scatter-gather the sp layout needs),
    and so does ANY quantized projection in ``layers`` — weight-only
    quantized layers keep raw int8/int4 codes in ``.weight`` with the
    scale in a separate buffer, so the fused entries (which read
    ``.weight`` directly) would silently drop the scales; their decode
    fusion is the int8/int4 matmul kernel inside the layer's own
    forward instead.  ``probe`` (called only under ``"auto"``) is the
    kernel's ``supported()`` shape gate: auto means "only where a
    kernel will actually serve", so a geometry the kernel declines
    (e.g. llama-1b's VMEM overflow) keeps the cheaper unfused path
    rather than paying the fused entry's recompute backward for an XLA
    composition forward."""
    if getattr(cfg, "sequence_parallel", False):
        return False
    if any(hasattr(l, "weight_scale") for l in layers):
        return False
    from ..ops import tuning
    mode = getattr(cfg, "fused_ops", "off")
    if not tuning.fusion_enabled(mode, op, key):
        return False
    if mode == "auto" and probe is not None and not probe():
        return False
    return True


class LlamaRMSNorm(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.eps = cfg.rms_norm_eps
        self.weight = self.create_parameter(
            (cfg.hidden_size,), default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.eps)


class LlamaAttention(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        h, hd = cfg.hidden_size, cfg.head_dim
        kv = cfg.num_key_value_heads * hd
        attr = _weight_attr(cfg)
        sp = cfg.sequence_parallel
        self.q_proj = ColumnParallelLinear(h, h, has_bias=False,
                                           weight_attr=attr, sequence_parallel=sp)
        self.k_proj = ColumnParallelLinear(h, kv, has_bias=False,
                                           weight_attr=attr, sequence_parallel=sp)
        self.v_proj = ColumnParallelLinear(h, kv, has_bias=False,
                                           weight_attr=attr, sequence_parallel=sp)
        self.o_proj = RowParallelLinear(h, h, has_bias=False,
                                        weight_attr=attr, sequence_parallel=sp)

    def forward(self, x, cos, sin, attn_mask=None, cache=None,
                seq_lens=None, block_tables=None, span_starts=None,
                norm_weight=None, lora=None):
        cfg = self.cfg
        b, s = x.shape[:2]
        roped = False
        # batched multi-LoRA (docs/SERVING.md "Multi-LoRA"): ``lora`` is
        # (per-layer stack pack, per-slot adapter ids).  Deltas inject
        # at the PROJECTION OUTPUTS — pre-RoPE for q/k, which is why the
        # LoRA path never takes the fused norm→qkv→rope kernel (the
        # decoder layer pins norm_weight=None when lora is threaded).
        from ..incubate.nn.functional import lora_delta

        def _o(t):
            y = self.o_proj(t)
            d = lora_delta(lora, t, "self_attn.o_proj")
            return y if d is None else y + d
        if norm_weight is not None:
            # fused RMSNorm→QKV→RoPE (docs/KERNELS.md): ``x`` is the
            # UN-NORMED residual stream — the decoder layer skipped its
            # input_layernorm and handed us its weight, so the fused op
            # reads the hidden states from HBM exactly once.  cos/sin
            # arrive (s, d) for the shared-position paths or (b, s, d)
            # for per-slot serving positions; either way the kernel
            # wants per-token (b·s, d) tables.
            from ..incubate.nn.functional import fused_rms_rope_qkv
            hd = cfg.head_dim
            if cos.ndim == 2:
                cos2 = jnp.broadcast_to(cos[None], (b, s, hd))
                sin2 = jnp.broadcast_to(sin[None], (b, s, hd))
            else:
                cos2, sin2 = cos, sin
            q, k, v = fused_rms_rope_qkv(
                x.reshape(b * s, cfg.hidden_size), norm_weight,
                self.q_proj.weight, self.k_proj.weight,
                self.v_proj.weight, cos2.reshape(b * s, hd),
                sin2.reshape(b * s, hd), hd, cfg.rms_norm_eps)
            q = q.reshape(b, s, cfg.num_attention_heads, hd)
            k = k.reshape(b, s, cfg.num_key_value_heads, hd)
            v = v.reshape(b, s, cfg.num_key_value_heads, hd)
            roped = True
        elif cfg.fuse_qkv_mlp and not cfg.sequence_parallel:
            # one [h, h+2kv] matmul instead of three — parameters stay
            # separate (HF import / TP specs untouched); the concat is a
            # cheap trace-time reshuffle XLA schedules once per step
            h_out = cfg.num_attention_heads * cfg.head_dim
            kv = cfg.num_key_value_heads * cfg.head_dim
            w = jnp.concatenate([self.q_proj.weight, self.k_proj.weight,
                                 self.v_proj.weight], axis=1)
            qkv = x @ w.astype(x.dtype)
            q, k, v = jnp.split(qkv, [h_out, h_out + kv], axis=-1)
            q = q.reshape(b, s, cfg.num_attention_heads, cfg.head_dim)
            k = k.reshape(b, s, cfg.num_key_value_heads, cfg.head_dim)
            v = v.reshape(b, s, cfg.num_key_value_heads, cfg.head_dim)
        else:
            q, k, v = self.q_proj(x), self.k_proj(x), self.v_proj(x)
            if lora is not None:
                # per-slot adapter deltas on the projection outputs
                # (pre-RoPE, pre-reshape — exactly where a merged
                # W + B_k A_k weight would land them); slot 0 rows add
                # an exact 0.0, keeping base requests bitwise unchanged
                dq = lora_delta(lora, x, "self_attn.q_proj")
                dk = lora_delta(lora, x, "self_attn.k_proj")
                dv = lora_delta(lora, x, "self_attn.v_proj")
                q = q if dq is None else q + dq
                k = k if dk is None else k + dk
                v = v if dv is None else v + dv
            q = q.reshape(b, s, cfg.num_attention_heads, cfg.head_dim)
            k = k.reshape(b, s, cfg.num_key_value_heads, cfg.head_dim)
            v = v.reshape(b, s, cfg.num_key_value_heads, cfg.head_dim)
        # heads are mp-sharded (they came from column-parallel projections)
        q = constrain(q, ("dp", "sharding"), None, "mp", None)
        k = constrain(k, ("dp", "sharding"), None, "mp", None)
        v = constrain(v, ("dp", "sharding"), None, "mp", None)
        if not roped:
            q, k = F.apply_rotary_pos_emb(q, k, cos, sin)
        if cache is not None and block_tables is not None:
            # paged KV pools (serving.Engine): the cache is the GLOBAL
            # (num_blocks, page, H_kv, D) pool pair (or int8 4-tuple),
            # addressed through this batch's block tables
            from ..incubate.nn.functional import (paged_decode_attend,
                                                  paged_prefill_write,
                                                  ragged_paged_attend)
            if span_starts is not None:
                # unified ragged step: each slot's span (prefill chunk
                # or decode token) writes at [start, start+len) and
                # every row attends its causal prefix — one dispatch
                # for the whole mixed batch
                out, new_cache = ragged_paged_attend(
                    cache, q, k, v, block_tables, span_starts, seq_lens)
                out = out.reshape(
                    b, s, cfg.num_attention_heads * cfg.head_dim)
                return _o(out), new_cache
            if s == 1 and seq_lens is not None:
                out, new_cache = paged_decode_attend(
                    cache, q[:, 0], k[:, 0], v[:, 0], block_tables,
                    seq_lens)
                out = out[:, None].reshape(
                    b, s, cfg.num_attention_heads * cfg.head_dim)
                return _o(out), new_cache
            # paged prefill: causal attention over the (bucket-padded)
            # prompt; pages written only at positions < seq_lens, so
            # padding rows never land in the pool
            plens = seq_lens if seq_lens is not None else \
                jnp.full((b,), s, jnp.int32)
            new_cache = paged_prefill_write(cache, k, v, block_tables,
                                            plens)
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            out = out.reshape(b, s, cfg.num_attention_heads * cfg.head_dim)
            return _o(out), new_cache
        if cache is not None and s == 1 and seq_lens is not None:
            # single-token decode against the dense KV cache (2-tuple fp
            # or int8-quantized 4-tuple) — shared cache-arity dispatch
            from ..incubate.nn.functional import decode_attend_cache
            out, new_cache = decode_attend_cache(
                cache, q[:, 0], k[:, 0], v[:, 0], seq_lens)
            out = out[:, None].reshape(b, s,
                                       cfg.num_attention_heads * cfg.head_dim)
            return _o(out), new_cache
        if cache is not None:
            # single-shot prefill: causal attention over the prompt, cache
            # written at [0, s) (chunked prefill lives in incubate's
            # FusedMultiTransformer; generate() prefills in one chunk)
            from ..incubate.nn.functional import prefill_write_cache
            new_cache = prefill_write_cache(cache, k, v)
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            out = out.reshape(b, s, cfg.num_attention_heads * cfg.head_dim)
            return _o(out), new_cache
        if cfg.context_parallel and attn_mask is None:
            from ..distributed import cp
            q = cp.split_sequence(q)
            k = cp.split_sequence(k)
            v = cp.split_sequence(v)
            out = cp.context_parallel_attention(q, k, v, causal=True,
                                                impl=cfg.context_parallel)
        else:
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                                 is_causal=attn_mask is None)
        out = out.reshape(b, s, cfg.num_attention_heads * cfg.head_dim)
        return _o(out)


class LlamaMLP(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        h, i = cfg.hidden_size, cfg.intermediate_size
        attr = _weight_attr(cfg)
        sp = cfg.sequence_parallel
        self.gate_proj = ColumnParallelLinear(h, i, has_bias=False,
                                              weight_attr=attr, sequence_parallel=sp)
        self.up_proj = ColumnParallelLinear(h, i, has_bias=False,
                                            weight_attr=attr, sequence_parallel=sp)
        self.down_proj = RowParallelLinear(i, h, has_bias=False,
                                           weight_attr=attr, sequence_parallel=sp)

    def forward(self, x, lora=None):
        cfg = self.cfg
        from ..ops.tuning import geom_key

        if lora is not None:
            # multi-LoRA serving: the gate/up deltas need x and the down
            # delta needs the swiglu intermediate, so the LoRA engine
            # pins the UNFUSED composition (the one-pass fused kernel
            # never materializes that intermediate) — the added fusion
            # here is the grouped BGMV itself
            from ..incubate.nn.functional import lora_delta

            g, u = self.gate_proj(x), self.up_proj(x)
            dg = lora_delta(lora, x, "mlp.gate_proj")
            du = lora_delta(lora, x, "mlp.up_proj")
            g = g if dg is None else g + dg
            u = u if du is None else u + du
            h = F.swiglu(g, u)
            y = self.down_proj(h)
            dd = lora_delta(lora, h, "mlp.down_proj")
            return y if dd is None else y + dd

        def _kernel_serves():
            from ..ops.pallas import fused_mlp as _fm
            return _fm.supported(x.reshape(-1, cfg.hidden_size),
                                 self.gate_proj.weight,
                                 self.down_proj.weight)

        if _use_fused(cfg, "fused_swiglu_mlp",
                      geom_key(h=cfg.hidden_size,
                               i=cfg.intermediate_size),
                      probe=_kernel_serves,
                      layers=(self.gate_proj, self.up_proj,
                              self.down_proj)):
            # one pass over the weights, the (T, I) gate/up intermediate
            # stays in VMEM on TPU (incubate fused entry; XLA
            # composition where the kernel cannot serve)
            from ..incubate.nn.functional import fused_swiglu_mlp
            lead = x.shape[:-1]
            y = fused_swiglu_mlp(x.reshape(-1, cfg.hidden_size),
                                 self.gate_proj.weight,
                                 self.up_proj.weight,
                                 self.down_proj.weight)
            return y.reshape(*lead, cfg.hidden_size)
        if cfg.fuse_qkv_mlp and not cfg.sequence_parallel:
            w = jnp.concatenate([self.gate_proj.weight, self.up_proj.weight],
                                axis=1)
            gu = x @ w.astype(x.dtype)
            g, u = jnp.split(gu, 2, axis=-1)
            return self.down_proj(F.swiglu(g, u))
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    returns_aux = False     # MoE variants return (x, aux_loss)
    supports_cache = True   # opt-in flag checked by init_cache/generate
    supports_paged = True   # paged-pool serving path (serving.Engine)

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.input_layernorm = LlamaRMSNorm(cfg)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = LlamaRMSNorm(cfg)
        self.mlp = LlamaMLP(cfg)

    def _attn_input(self, x):
        """(attention input, norm_weight kwarg): under the fused qkv op
        the layernorm folds INTO the attention projection — hand the raw
        residual stream plus the norm weight down instead of norming
        here (resolved at trace time, ops.tuning)."""
        cfg = self.cfg
        from ..ops.tuning import geom_key
        hd = cfg.head_dim
        key = geom_key(h=cfg.hidden_size,
                       nq=cfg.num_attention_heads * hd,
                       nk=cfg.num_key_value_heads * hd, hd=hd)
        attn = self.self_attn

        def _kernel_serves():
            from ..ops.pallas import fused_norm_qkv as _fq
            return _fq.supported(x.reshape(-1, cfg.hidden_size),
                                 attn.q_proj.weight, attn.k_proj.weight,
                                 hd)

        if _use_fused(cfg, "fused_rms_rope_qkv", key,
                      probe=_kernel_serves,
                      layers=(attn.q_proj, attn.k_proj, attn.v_proj)):
            return x, self.input_layernorm.weight
        return self.input_layernorm(x), None

    def _use_mega(self, x, cache) -> bool:
        """Trace-time gate for the decode megakernel (the whole
        attention block as one dispatch — ops/pallas/mega_decode.py).
        ``"mega"`` always takes the entry point (which still falls back
        to its XLA composition where the kernel cannot serve, e.g. int8
        KV pools); ``"auto"`` takes it only when the kernel will
        actually run — dispatch live AND ``supported()`` accepting this
        geometry, pool and VMEM footprint.  Quantized projections and
        sequence parallel step aside inside ``_use_fused``; the LoRA
        path never reaches here (the caller pins unfused)."""
        cfg = self.cfg
        mode = getattr(cfg, "fused_ops", "off")
        if mode not in ("mega", "auto"):
            return False
        from ..ops.tuning import geom_key
        hd = cfg.head_dim
        key = geom_key(h=cfg.hidden_size,
                       nq=cfg.num_attention_heads * hd,
                       nk=cfg.num_key_value_heads * hd, hd=hd)
        attn = self.self_attn

        def _kernel_serves():
            from ..ops.pallas import mega_decode as _md
            return _md.supported(x, attn.q_proj.weight,
                                 attn.k_proj.weight, attn.o_proj.weight,
                                 hd, cache=cache)

        return _use_fused(cfg, "mega_decode_layer", key,
                          probe=_kernel_serves,
                          layers=(attn.q_proj, attn.k_proj, attn.v_proj,
                                  attn.o_proj))

    def forward(self, x, cos, sin, attn_mask=None, cache=None,
                seq_lens=None, block_tables=None, span_starts=None,
                lora=None):
        if cache is not None:
            if (span_starts is not None and block_tables is not None
                    and lora is None and self._use_mega(x, cache)):
                # decode megakernel: the whole attention block — norm →
                # qkv → rope → ragged paged attention → o_proj +
                # residual — as ONE entry point (one Pallas dispatch on
                # TPU, the pinned XLA composition elsewhere)
                from ..incubate.nn.functional import mega_decode_layer
                cfg = self.cfg
                b, s = x.shape[:2]
                hd = cfg.head_dim
                if cos.ndim == 2:
                    cos2 = jnp.broadcast_to(cos[None], (b, s, hd))
                    sin2 = jnp.broadcast_to(sin[None], (b, s, hd))
                else:
                    cos2, sin2 = cos, sin
                attn = self.self_attn
                x, cache = mega_decode_layer(
                    x, self.input_layernorm.weight, attn.q_proj.weight,
                    attn.k_proj.weight, attn.v_proj.weight,
                    attn.o_proj.weight, cos2, sin2, cache, block_tables,
                    span_starts, seq_lens, hd, cfg.rms_norm_eps)
                x = x + self.mlp(self.post_attention_layernorm(x),
                                 lora=lora)
                return x, cache
            if lora is None:
                attn_in, nw = self._attn_input(x)
            else:
                # LoRA deltas inject pre-RoPE at the projection outputs,
                # which the fused norm→qkv→rope single pass cannot
                # expose — the multi-LoRA engine pins the unfused path
                attn_in, nw = self.input_layernorm(x), None
            attn, cache = self.self_attn(attn_in, cos, sin,
                                         attn_mask, cache=cache,
                                         seq_lens=seq_lens,
                                         block_tables=block_tables,
                                         span_starts=span_starts,
                                         norm_weight=nw, lora=lora)
            x = x + attn
            x = x + self.mlp(self.post_attention_layernorm(x),
                             lora=lora)
            return x, cache
        # named scopes → readable xprof/Perfetto traces (profiler facade)
        with jax.named_scope("attn"):
            attn_in, nw = self._attn_input(x)
            x = x + self.self_attn(attn_in, cos, sin, attn_mask,
                                   norm_weight=nw)
        with jax.named_scope("mlp"):
            x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    decoder_layer_cls: type = None  # set below; subclasses override

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        cls = type(self).decoder_layer_cls
        self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        from ..nn.layers_common import LayerList
        if cfg.recompute_num_layers is not None and not (
                0 < cfg.recompute_num_layers <= cfg.num_hidden_layers):
            raise ValueError(
                f"recompute_num_layers={cfg.recompute_num_layers} must be in "
                f"[1, num_hidden_layers={cfg.num_hidden_layers}]")
        if cfg.recompute_num_layers is not None and not cfg.use_recompute \
                and cfg.pipeline_stages <= 1:
            # ADVICE r5: the partial-remat count only takes effect under
            # use_recompute=True — say so instead of silently ignoring it
            # (under pipeline the combination is rejected outright below)
            warnings.warn(
                f"recompute_num_layers={cfg.recompute_num_layers} is "
                "ignored because use_recompute=False — set "
                "use_recompute=True to remat the first N layers",
                UserWarning, stacklevel=2)
        if cfg.pipeline_stages > 1:
            if cfg.recompute_num_layers is not None:
                raise NotImplementedError(
                    "recompute_num_layers applies per stacked layer; the "
                    "pp-scanned body remats uniformly — drop "
                    "recompute_num_layers under pipeline_stages > 1")
            # pipeline-parallel body: per-layer params stacked and sharded
            # over the pp mesh axis (distributed/pipeline.py)
            from ..distributed.pipeline import StackedPipelineStages
            self.layers = StackedPipelineStages(
                lambda: cls(cfg), cfg.num_hidden_layers,
                num_stages=cfg.pipeline_stages,
                num_microbatches=cfg.num_microbatches,
                num_virtual_pipeline_stages=cfg.virtual_pp_degree,
                use_recompute=cfg.use_recompute,
                recompute_policy=cfg.recompute_policy,
                extra_is_batched=(False, False, True),
                has_aux=getattr(cls, "returns_aux", False))
        else:
            layers = []
            for i in range(cfg.num_hidden_layers):
                layer = cls(cfg)
                # partial remat (Megatron's --recompute-num-layers): the
                # non-rematted tail keeps its activations, trading leftover
                # HBM for recompute FLOPs layer by layer
                if cfg.use_recompute and (cfg.recompute_num_layers is None
                                          or i < cfg.recompute_num_layers):
                    layer = RecomputeWrapper(layer, policy=cfg.recompute_policy)
                layers.append(layer)
            self.layers = LayerList(layers)
        self.norm = LlamaRMSNorm(cfg)

    def init_cache(self, batch, max_len, dtype=None):
        """Per-layer dense (k, v) caches for cached generation; dtype
        defaults to the config dtype (bf16 configs get bf16 caches)."""
        cfg = self.cfg
        if cfg.pipeline_stages > 1:
            raise NotImplementedError(
                "cached generation requires pipeline_stages == 1")
        if not getattr(type(self).decoder_layer_cls, "supports_cache",
                       False):
            raise NotImplementedError(
                f"{type(self).decoder_layer_cls.__name__} does not support "
                "KV caches (generate() falls back to full recompute)")
        from .generation import make_dense_caches
        return make_dense_caches(
            cfg.num_hidden_layers, batch, max_len,
            cfg.num_key_value_heads, cfg.head_dim,
            dtype if dtype is not None else cfg.dtype)

    def forward(self, input_ids, attn_mask=None, position_ids=None,
                caches=None, seq_lens=None, block_tables=None,
                span_starts=None, lora=None):
        cfg = self.cfg
        if caches is not None:
            if attn_mask is not None or position_ids is not None:
                raise NotImplementedError(
                    "cached forward supports dense causal prefill/decode "
                    "only — attn_mask/position_ids would be silently "
                    "ignored (left-pad or trim prompts instead)")
            return self._forward_cached(input_ids, caches, seq_lens,
                                        block_tables, span_starts, lora)
        x = self.embed_tokens(input_ids)
        cos, sin = F.rope_cos_sin(input_ids.shape[1], cfg.head_dim,
                                  base=cfg.rope_theta, dtype=x.dtype,
                                  position_ids=position_ids)
        aux = 0.0
        if cfg.pipeline_stages > 1:
            x = self.layers(x, cos, sin, attn_mask)
            if isinstance(x, tuple):
                x, aux = x
        else:
            for layer in self.layers:
                x = layer(x, cos, sin, attn_mask)
                if isinstance(x, tuple):
                    x, a = x
                    aux = aux + a
        # same-trace stash consumed by the CausalLM head (no transform
        # boundary between model and head, so this is legal under jit)
        self.__dict__["_moe_aux"] = aux
        return self.norm(x)

    def _forward_cached(self, input_ids, caches, seq_lens,
                        block_tables=None, span_starts=None, lora=None):
        """Prefill (seq_lens None) or one-token decode against the caches.
        With ``block_tables`` the caches are paged pools (serving path):
        prefill also takes ``seq_lens`` as the real prompt lengths so
        padding never lands in the pool.  With ``span_starts`` the batch
        is the unified RAGGED serving step: per-slot spans (chunked
        prefill or decode tokens) at positions ``[start, start+len)``,
        ``seq_lens`` carrying the span lengths.  ``lora`` is the
        multi-LoRA pair (per-layer stacked adapter packs, per-slot
        adapter ids) — each decoder layer consumes its own pack.
        Returns (hidden, new_caches)."""
        cfg = self.cfg
        x = self.embed_tokens(input_ids)
        b, s = input_ids.shape
        decode = (s == 1 and seq_lens is not None)
        if span_starts is not None:
            # per-slot positions: the span's tokens sit at start..start+s
            cos, sin = F.rope_cos_sin(
                s, cfg.head_dim, base=cfg.rope_theta, dtype=x.dtype,
                position_ids=span_starts[:, None] + jnp.arange(s)[None, :])
        elif decode:
            cos, sin = F.rope_cos_sin(1, cfg.head_dim, base=cfg.rope_theta,
                                      dtype=x.dtype,
                                      position_ids=seq_lens[:, None])
        else:
            cos, sin = F.rope_cos_sin(s, cfg.head_dim, base=cfg.rope_theta,
                                      dtype=x.dtype)
        # the paged kwargs are only threaded when present: decoder-layer
        # subclasses without paged support (MoE) keep their signature
        kw = {} if block_tables is None else {"block_tables": block_tables}
        if span_starts is not None:
            kw["span_starts"] = span_starts
        lens_arg = seq_lens if (decode or block_tables is not None) \
            else None
        # per-layer LoRA packs: run_cached_layers walks the stack in
        # order, so a sequential iterator hands each layer its own pack
        # at trace time (adapter ids are shared batch data)
        lit = iter(lora[0]) if lora is not None else None
        laids = lora[1] if lora is not None else None
        from .generation import run_cached_layers
        x, new_caches = run_cached_layers(
            self.layers, x, caches,
            lambda inner, x, cache: inner(
                x, cos, sin, cache=cache, seq_lens=lens_arg,
                lora=None if lit is None else (next(lit), laids), **kw))
        self.__dict__["_moe_aux"] = 0.0
        return self.norm(x), new_caches


class LlamaForCausalLM(CachedGenerationMixin, Layer):
    model_cls: type = None  # set below; subclasses override

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = type(self).model_cls(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(cfg.hidden_size, cfg.vocab_size,
                                                has_bias=False,
                                                weight_attr=_weight_attr(cfg))
        self.loss_fn = ParallelCrossEntropy(ignore_index=-100)

    def logits(self, hidden):
        if self.cfg.tie_word_embeddings:
            w = self.model.embed_tokens.weight  # (vocab, hidden), mp on vocab
            logits = hidden @ w.T
            return constrain(logits, ("dp", "sharding"), None, "mp")
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, attn_mask=None, position_ids=None):
        hidden = self.model(input_ids, attn_mask, position_ids)
        if labels is None:
            return self.logits(hidden)
        chunks = self.cfg.loss_seq_chunks
        if chunks > 1:
            if hidden.shape[1] % chunks == 0:
                return self._chunked_loss(hidden, labels, chunks)
            import warnings
            warnings.warn(
                f"loss_seq_chunks={chunks} does not divide seq_len="
                f"{hidden.shape[1]}; falling back to the monolithic "
                "[B,S,V] logits path (full logits WILL be materialized)",
                stacklevel=2)
        logits = self.logits(hidden)
        loss = self.loss_fn(logits.astype(jnp.float32), labels)
        valid = (labels != -100)
        return jnp.sum(loss * valid) / jnp.maximum(jnp.sum(valid), 1)

    def _chunked_loss(self, hidden, labels, chunks):
        """Memory-efficient vocab CE: the [B,S,V] logits tensor (the
        single largest activation — ~1 GiB fp32 at bs4/seq2048/32k vocab)
        is never materialized. Each sequence chunk's logits are computed,
        reduced to a loss sum, and rematerialized in the backward pass
        (one extra lm_head matmul, ~3% of step FLOPs, for a ~2-3 GiB HBM
        highwater cut that buys a larger batch). Chunking is along the
        sequence axis so vocab-parallel (mp) sharding is untouched."""
        s_chunk = hidden.shape[1] // chunks

        @jax.checkpoint
        def chunk_sums(h, l):
            logits = self.logits(h)
            loss = self.loss_fn(logits.astype(jnp.float32), l)
            valid = (l != -100)
            return jnp.sum(loss * valid), jnp.sum(valid)

        total = jnp.float32(0.0)
        count = jnp.int32(0)
        for c in range(chunks):  # unrolled: XLA overlaps chunk pipelines
            sl = slice(c * s_chunk, (c + 1) * s_chunk)
            s, n = chunk_sums(hidden[:, sl], labels[:, sl])
            total += s
            count += n
        return total / jnp.maximum(count, 1)

    def _cache_supported(self) -> bool:
        return (self.cfg.pipeline_stages == 1
                and getattr(type(self.model).decoder_layer_cls,
                            "supports_cache", False))


LlamaModel.decoder_layer_cls = LlamaDecoderLayer
LlamaForCausalLM.model_cls = LlamaModel


def llama(name_or_config="tiny", **overrides) -> LlamaForCausalLM:
    cfg = (PRESETS[name_or_config] if isinstance(name_or_config, str)
           else name_or_config)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return LlamaForCausalLM(cfg)


def causal_lm_loss(model, batch):
    """Standard loss_fn for TrainStep."""
    return model(batch["input_ids"], labels=batch["labels"])
