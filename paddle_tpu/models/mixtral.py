"""Mixtral-style sparse-MoE causal LM (BASELINE.json configs[2]: MoE with
EP all-to-all).

Reference capability: the MoE model family the reference core enables via
incubate/distributed/models/moe (the full model lives in PaddleNLP —
SURVEY.md §0 scope note).  Reuses the Llama blocks; the MLP becomes an
expert-parallel MoELayer routed by a GShard/Switch gate.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from ..distributed.moe import GATES, MoELayer
from ..nn.layer import Layer
from .llama import (LlamaAttention, LlamaConfig, LlamaForCausalLM, LlamaMLP,
                    LlamaModel, LlamaRMSNorm)


@dataclasses.dataclass
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    gate: str = "gshard"            # "gshard" (top-2) | "switch" (top-1)
    capacity_factor: float = 1.25
    router_aux_loss_coef: float = 0.01


PRESETS = {
    "mixtral-8x7b": MixtralConfig(
        hidden_size=4096, intermediate_size=14336, num_hidden_layers=32,
        num_attention_heads=32, num_key_value_heads=8, num_experts=8),
    "tiny": MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, num_experts=4, capacity_factor=2.0),
}


class MixtralDecoderLayer(Layer):
    returns_aux = True      # train forward returns (x, router_aux_loss)
    supports_cache = True   # cached inference (router aux ignored)

    def __init__(self, cfg: MixtralConfig):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(cfg)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = LlamaRMSNorm(cfg)
        self.block_sparse_moe = MoELayer(
            cfg.hidden_size, expert=lambda: LlamaMLP(cfg),
            num_experts=cfg.num_experts, gate=cfg.gate, top_k=cfg.top_k,
            capacity_factor=cfg.capacity_factor)

    def forward(self, x, cos, sin, attn_mask=None, cache=None,
                seq_lens=None):
        if cache is not None:
            # cached inference: attention uses the KV cache; the MoE block
            # is per-token so it works unchanged (router aux is an
            # inference no-op)
            attn, cache = self.self_attn(self.input_layernorm(x), cos, sin,
                                         attn_mask, cache=cache,
                                         seq_lens=seq_lens)
            x = x + attn
            x = x + self.block_sparse_moe(self.post_attention_layernorm(x))
            return x, cache
        x = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        x = x + self.block_sparse_moe(self.post_attention_layernorm(x))
        # aux read immediately after the call, same trace level (the
        # MoELayer contract), then threaded outward through our output
        return x, self.block_sparse_moe.aux_loss


class MixtralModel(LlamaModel):
    decoder_layer_cls = MixtralDecoderLayer


class MixtralForCausalLM(LlamaForCausalLM):
    model_cls = MixtralModel

    def forward(self, input_ids, labels=None, attn_mask=None,
                position_ids=None):
        out = super().forward(input_ids, labels=labels, attn_mask=attn_mask,
                              position_ids=position_ids)
        if labels is None:
            return out  # inference ignores the router loss
        return out + self.cfg.router_aux_loss_coef * self.model._moe_aux


def mixtral(name_or_config="tiny", **overrides) -> MixtralForCausalLM:
    cfg = (PRESETS[name_or_config] if isinstance(name_or_config, str)
           else name_or_config)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return MixtralForCausalLM(cfg)


def causal_lm_loss(model, batch):
    return model(batch["input_ids"], labels=batch["labels"])
