"""SDXL-style diffusion UNet (BASELINE.json configs[3]: "SDXL conv/attn").

Reference capability: Stable-Diffusion-XL UNet served by PaddleMIX on the
reference stack (the core repo provides conv/groupnorm/attention kernels —
SURVEY.md §0 scope note; §2.1 fused kernels row). Architecture follows the
public SDXL design: ResNet blocks (GroupNorm→SiLU→Conv), spatial
transformer blocks with self+cross attention and GEGLU FFN, sinusoidal
time embedding + SDXL's added pooled-text/size conditioning, skip-connected
down/up path.

TPU-first: everything is one jit program; convs lower to XLA convs on the
MXU; attention uses the framework's flash-attention dispatch (Pallas on
TPU); channels-last compute is left to XLA layout assignment (API stays
NCHW for porting parity).
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Tuple

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers_common import (Conv2D, GroupNorm, LayerList, LayerNorm,
                                Linear)


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280)
    layers_per_block: int = 2
    transformer_depth: Tuple[int, ...] = (0, 2, 10)  # per level
    num_attention_heads: Tuple[int, ...] = (5, 10, 20)
    cross_attention_dim: int = 2048
    addition_time_embed_dim: int = 256     # SDXL micro-conditioning
    projection_class_embeddings_input_dim: int = 2816
    norm_num_groups: int = 32
    sample_size: int = 128


PRESETS = {
    "sdxl": UNetConfig(),
    "sd15": UNetConfig(block_out_channels=(320, 640, 1280, 1280),
                       transformer_depth=(1, 1, 1, 0),
                       num_attention_heads=(8, 8, 8, 8),
                       cross_attention_dim=768,
                       projection_class_embeddings_input_dim=0),
    "tiny": UNetConfig(block_out_channels=(32, 64),
                       layers_per_block=1,
                       transformer_depth=(0, 1),
                       num_attention_heads=(2, 4),
                       cross_attention_dim=64,
                       norm_num_groups=8,
                       addition_time_embed_dim=32,
                       projection_class_embeddings_input_dim=96,
                       sample_size=16),
}


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal embedding, (B,) → (B, dim)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class GEGLU(Layer):
    def __init__(self, dim_in, dim_out):
        super().__init__()
        self.proj = Linear(dim_in, dim_out * 2)

    def forward(self, x):
        h, gate = jnp.split(self.proj(x), 2, axis=-1)
        return h * F.gelu(gate)


class CrossAttention(Layer):
    """q from image tokens, k/v from `context` (or self-attn when None)."""

    def __init__(self, query_dim, context_dim=None, heads=8, dim_head=64):
        super().__init__()
        inner = heads * dim_head
        context_dim = context_dim or query_dim
        self.heads, self.dim_head = heads, dim_head
        self.to_q = Linear(query_dim, inner, bias_attr=False)
        self.to_k = Linear(context_dim, inner, bias_attr=False)
        self.to_v = Linear(context_dim, inner, bias_attr=False)
        self.to_out = Linear(inner, query_dim)

    def forward(self, x, context=None):
        context = x if context is None else context
        b, n = x.shape[:2]
        m = context.shape[1]
        q = self.to_q(x).reshape(b, n, self.heads, self.dim_head)
        k = self.to_k(context).reshape(b, m, self.heads, self.dim_head)
        v = self.to_v(context).reshape(b, m, self.heads, self.dim_head)
        out = F.scaled_dot_product_attention(q, k, v)
        return self.to_out(out.reshape(b, n, self.heads * self.dim_head))


class BasicTransformerBlock(Layer):
    def __init__(self, dim, context_dim, heads, dim_head):
        super().__init__()
        self.norm1 = LayerNorm(dim)
        self.attn1 = CrossAttention(dim, None, heads, dim_head)          # self
        self.norm2 = LayerNorm(dim)
        self.attn2 = CrossAttention(dim, context_dim, heads, dim_head)   # cross
        self.norm3 = LayerNorm(dim)
        self.ff = GEGLU(dim, dim * 4)
        self.ff_out = Linear(dim * 4, dim)

    def forward(self, x, context):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), context)
        x = x + self.ff_out(self.ff(self.norm3(x)))
        return x


class SpatialTransformer(Layer):
    """(B,C,H,W) → tokens → depth × BasicTransformerBlock → back."""

    def __init__(self, channels, depth, heads, context_dim, groups):
        super().__init__()
        dim_head = channels // heads
        self.norm = GroupNorm(groups, channels)
        self.proj_in = Linear(channels, channels)
        self.blocks = LayerList([
            BasicTransformerBlock(channels, context_dim, heads, dim_head)
            for _ in range(depth)])
        self.proj_out = Linear(channels, channels)

    def forward(self, x, context):
        b, c, h, w = x.shape
        residual = x
        x = self.norm(x)
        x = x.transpose(0, 2, 3, 1).reshape(b, h * w, c)
        x = self.proj_in(x)
        for blk in self.blocks:
            x = blk(x, context)
        x = self.proj_out(x)
        x = x.reshape(b, h, w, c).transpose(0, 3, 1, 2)
        return x + residual


class ResBlock(Layer):
    def __init__(self, in_ch, out_ch, temb_ch, groups):
        super().__init__()
        self.norm1 = GroupNorm(groups, in_ch)
        self.conv1 = Conv2D(in_ch, out_ch, 3, padding=1)
        self.time_emb_proj = Linear(temb_ch, out_ch)
        self.norm2 = GroupNorm(groups, out_ch)
        self.conv2 = Conv2D(out_ch, out_ch, 3, padding=1)
        self.skip = (Conv2D(in_ch, out_ch, 1) if in_ch != out_ch else None)

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.time_emb_proj(F.silu(temb))[:, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        if self.skip is not None:
            x = self.skip(x)
        return x + h


class Downsample(Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = Conv2D(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class Upsample2x(Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = Conv2D(ch, ch, 3, padding=1)

    def forward(self, x):
        x = F.interpolate(x, scale_factor=2, mode="nearest")
        return self.conv(x)


class SDXLUNet(Layer):
    """unet(sample, timestep, encoder_hidden_states[, added_cond]) → eps."""

    def __init__(self, config: UNetConfig):
        super().__init__()
        self.config = cfg = config
        chs = cfg.block_out_channels
        temb_ch = chs[0] * 4
        g = cfg.norm_num_groups

        self.conv_in = Conv2D(cfg.in_channels, chs[0], 3, padding=1)
        self.time_lin1 = Linear(chs[0], temb_ch)
        self.time_lin2 = Linear(temb_ch, temb_ch)
        if cfg.projection_class_embeddings_input_dim:
            self.add_lin1 = Linear(cfg.projection_class_embeddings_input_dim,
                                   temb_ch)
            self.add_lin2 = Linear(temb_ch, temb_ch)

        # down path
        self.down_res: List = []
        self.down_attn: List = []
        self.downsamplers: List = []
        ch = chs[0]
        self._skip_chs = [ch]
        for level, out_ch in enumerate(chs):
            for i in range(cfg.layers_per_block):
                res = ResBlock(ch, out_ch, temb_ch, g)
                self.add_sublayer(f"down_{level}_{i}_res", res)
                attn = None
                if cfg.transformer_depth[level] > 0:
                    attn = SpatialTransformer(
                        out_ch, cfg.transformer_depth[level],
                        cfg.num_attention_heads[level],
                        cfg.cross_attention_dim, g)
                    self.add_sublayer(f"down_{level}_{i}_attn", attn)
                self.down_res.append(res)
                self.down_attn.append(attn)
                ch = out_ch
                self._skip_chs.append(ch)
            if level < len(chs) - 1:
                d = Downsample(ch)
                self.add_sublayer(f"down_{level}_ds", d)
                self.downsamplers.append(d)
                self._skip_chs.append(ch)
            else:
                self.downsamplers.append(None)

        # middle
        self.mid_res1 = ResBlock(ch, ch, temb_ch, g)
        self.mid_attn = SpatialTransformer(
            ch, max(1, cfg.transformer_depth[-1]),
            cfg.num_attention_heads[-1], cfg.cross_attention_dim, g)
        self.mid_res2 = ResBlock(ch, ch, temb_ch, g)

        # up path (reversed levels, layers_per_block+1 resblocks each)
        self.up_res: List = []
        self.up_attn: List = []
        self.upsamplers: List = []
        skip_chs = list(self._skip_chs)
        for level, out_ch in list(enumerate(chs))[::-1]:
            for i in range(cfg.layers_per_block + 1):
                skip = skip_chs.pop()
                res = ResBlock(ch + skip, out_ch, temb_ch, g)
                self.add_sublayer(f"up_{level}_{i}_res", res)
                attn = None
                if cfg.transformer_depth[level] > 0:
                    attn = SpatialTransformer(
                        out_ch, cfg.transformer_depth[level],
                        cfg.num_attention_heads[level],
                        cfg.cross_attention_dim, g)
                    self.add_sublayer(f"up_{level}_{i}_attn", attn)
                self.up_res.append(res)
                self.up_attn.append(attn)
                ch = out_ch
            if level > 0:
                u = Upsample2x(ch)
                self.add_sublayer(f"up_{level}_us", u)
                self.upsamplers.append(u)
            else:
                self.upsamplers.append(None)

        self.norm_out = GroupNorm(g, ch)
        self.conv_out = Conv2D(ch, cfg.out_channels, 3, padding=1)

    def forward(self, sample, timestep, encoder_hidden_states,
                added_cond=None):
        """``added_cond`` is either the pre-built conditioning vector of
        size projection_class_embeddings_input_dim, or the SDXL pair
        ``(text_embeds, time_ids)`` — time_ids (B, 6) micro-conditioning is
        sinusoidally embedded at addition_time_embed_dim per id and
        concatenated with the pooled text embedding."""
        cfg = self.config
        temb = timestep_embedding(timestep, cfg.block_out_channels[0])
        temb = self.time_lin2(F.silu(self.time_lin1(temb)))
        if cfg.projection_class_embeddings_input_dim and added_cond is not None:
            if isinstance(added_cond, (tuple, list)):
                text_embeds, time_ids = added_cond
                b = time_ids.shape[0]
                ids = timestep_embedding(time_ids.reshape(-1),
                                         cfg.addition_time_embed_dim)
                ids = ids.reshape(b, -1)
                added_cond = jnp.concatenate(
                    [text_embeds, ids.astype(text_embeds.dtype)], axis=-1)
            temb = temb + self.add_lin2(F.silu(self.add_lin1(added_cond)))

        h = self.conv_in(sample)
        skips = [h]
        idx = 0
        for level in range(len(cfg.block_out_channels)):
            for _ in range(cfg.layers_per_block):
                h = self.down_res[idx](h, temb)
                if self.down_attn[idx] is not None:
                    h = self.down_attn[idx](h, encoder_hidden_states)
                skips.append(h)
                idx += 1
            if self.downsamplers[level] is not None:
                h = self.downsamplers[level](h)
                skips.append(h)

        h = self.mid_res1(h, temb)
        h = self.mid_attn(h, encoder_hidden_states)
        h = self.mid_res2(h, temb)

        idx = 0
        for pos, level in enumerate(range(len(cfg.block_out_channels))[::-1]):
            for _ in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=1)
                h = self.up_res[idx](h, temb)
                if self.up_attn[idx] is not None:
                    h = self.up_attn[idx](h, encoder_hidden_states)
                idx += 1
            if self.upsamplers[pos] is not None:
                h = self.upsamplers[pos](h)

        return self.conv_out(F.silu(self.norm_out(h)))


def sdxl_unet(preset: str = "sdxl") -> SDXLUNet:
    return SDXLUNet(PRESETS[preset])
