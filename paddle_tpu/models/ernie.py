"""ERNIE encoder family (the flagship Paddle-ecosystem model line).

Reference capability: PaddleNLP paddlenlp/transformers/ernie/modeling.py
(`ErnieModel`) — BASELINE.json config[1] names ERNIE explicitly.  ERNIE
3.0's public encoder is the BERT computation plus a task-type embedding
(``use_task_id``); the decoder-only ERNIE 3.5 scale path is covered by the
GPT/Llama families (tests/test_baseline_configs.py cfg2 runs the 13B-class
TP+PP geometry).

Module names mirror the HF ``ErnieModel`` layout so ``models.hf.from_hf``
imports checkpoints by pure transpose, and the torch-oracle parity test
pins the wiring (tests/test_hf_convert.py::TestHfErnie).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from ..nn.layers_common import Embedding
from .bert import BertConfig, BertModel, _Embeddings

__all__ = ["ErnieConfig", "ErnieModel", "ernie"]


@dataclasses.dataclass
class ErnieConfig(BertConfig):
    task_type_vocab_size: int = 3
    use_task_id: bool = True


PRESETS = {
    "tiny": ErnieConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, intermediate_size=128,
                        max_position_embeddings=64, hidden_dropout=0.0,
                        attention_dropout=0.0),
    # ERNIE 3.0 public encoder sizes (PaddleNLP model cards)
    "ernie-3.0-base": ErnieConfig(vocab_size=40000, hidden_size=768,
                                  num_hidden_layers=12,
                                  num_attention_heads=12,
                                  intermediate_size=3072,
                                  max_position_embeddings=2048),
    "ernie-3.0-medium": ErnieConfig(vocab_size=40000, hidden_size=768,
                                    num_hidden_layers=6,
                                    num_attention_heads=12,
                                    intermediate_size=3072,
                                    max_position_embeddings=2048),
    "ernie-3.0-micro": ErnieConfig(vocab_size=40000, hidden_size=384,
                                   num_hidden_layers=4,
                                   num_attention_heads=12,
                                   intermediate_size=1536,
                                   max_position_embeddings=2048),
}


class _ErnieEmbeddings(_Embeddings):
    """BERT embeddings + ERNIE's task-type embedding (use_task_id) — the
    shared word/position/type + LayerNorm path lives in bert._Embeddings
    so a fix there covers both families."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__(cfg)
        self.use_task_id = cfg.use_task_id
        if cfg.use_task_id:
            self.task_type_embeddings = Embedding(cfg.task_type_vocab_size,
                                                  cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        x = self._sum(input_ids, token_type_ids, position_ids)
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = jnp.zeros(input_ids.shape, jnp.int32)
            # the task term joins BEFORE the shared LayerNorm
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.LayerNorm(x))


class ErnieModel(BertModel):
    """BertModel with the ERNIE embedding block (embeddings_class hook);
    mask handling, encoder and pooler are inherited."""

    embeddings_class = _ErnieEmbeddings

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        """→ (sequence_output [b,s,h], pooled_output [b,h]) — the
        PaddleNLP ErnieModel return shape."""
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        x = self.encoder(x, self._additive_mask(attention_mask))
        return x, self.pooler(x)


def ernie(name_or_config="tiny", **overrides) -> ErnieModel:
    cfg = (PRESETS[name_or_config] if isinstance(name_or_config, str)
           else name_or_config)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return ErnieModel(cfg)
