"""BERT-family encoder (PaddleNLP ``BertModel`` scope).

Reference capability: PaddleNLP paddlenlp/transformers/bert/modeling.py
(the encoder workhorse of the Paddle ecosystem; SURVEY §0 scope note).
Module names deliberately mirror the HF layout
(``encoder.layer.N.attention.self.query`` …) so ``models.hf.from_hf``
imports HF BERT checkpoints by pure transpose, and the torch-oracle
parity test pins the architecture.

TPU notes: post-LN encoder traces to one XLA program; attention uses the
shared scaled_dot_product_attention path (flash kernel when applicable,
bidirectional here so the XLA fallback's full matmul is the right call —
no causal skipping to exploit).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers_common import Dropout, Embedding, LayerNorm, Linear

__all__ = ["BertConfig", "BertModel", "bert"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


PRESETS = {
    "tiny": BertConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=4, intermediate_size=128,
                       max_position_embeddings=64,
                       hidden_dropout=0.0, attention_dropout=0.0),
    "bert-base": BertConfig(),
    "bert-large": BertConfig(hidden_size=1024, num_hidden_layers=24,
                             num_attention_heads=16, intermediate_size=4096),
}


class _Embeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        self.LayerNorm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout)

    def _sum(self, input_ids, token_type_ids=None, position_ids=None):
        """Pre-norm embedding sum — shared with subclasses (ERNIE) that
        add extra terms before the LayerNorm."""
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = jnp.arange(s)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros((b, s), jnp.int32)
        return (self.word_embeddings(input_ids)
                + self.position_embeddings(position_ids)
                + self.token_type_embeddings(token_type_ids))

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        return self.dropout(self.LayerNorm(
            self._sum(input_ids, token_type_ids, position_ids)))


class _SelfAttention(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.query = Linear(cfg.hidden_size, cfg.hidden_size)
        self.key = Linear(cfg.hidden_size, cfg.hidden_size)
        self.value = Linear(cfg.hidden_size, cfg.hidden_size)
        self.nh, self.hd = cfg.num_attention_heads, cfg.head_dim
        self.p = cfg.attention_dropout

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        q = self.query(x).reshape(b, s, self.nh, self.hd)
        k = self.key(x).reshape(b, s, self.nh, self.hd)
        v = self.value(x).reshape(b, s, self.nh, self.hd)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.p,
            training=self.training)
        return out.reshape(b, s, h)


class _AttentionOutput(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)
        self.LayerNorm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout)

    def forward(self, x, residual):
        return self.LayerNorm(residual + self.dropout(self.dense(x)))


class _Attention(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.self = _SelfAttention(cfg)
        self.output = _AttentionOutput(cfg)

    def forward(self, x, attn_mask=None):
        return self.output(self.self(x, attn_mask), x)


class _Intermediate(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.intermediate_size)

    def forward(self, x):
        return F.gelu(self.dense(x))


class _Output(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.intermediate_size, cfg.hidden_size)
        self.LayerNorm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout)

    def forward(self, x, residual):
        return self.LayerNorm(residual + self.dropout(self.dense(x)))


class _EncoderLayer(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attention = _Attention(cfg)
        self.intermediate = _Intermediate(cfg)
        self.output = _Output(cfg)

    def forward(self, x, attn_mask=None):
        x = self.attention(x, attn_mask)
        return self.output(self.intermediate(x), x)


class _Encoder(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        from ..nn.layers_common import LayerList
        self.layer = LayerList([_EncoderLayer(cfg)
                                for _ in range(cfg.num_hidden_layers)])

    def forward(self, x, attn_mask=None):
        for lyr in self.layer:
            x = lyr(x, attn_mask)
        return x


class _Pooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, x):
        return jnp.tanh(self.dense(x[:, 0]))


class BertModel(Layer):
    embeddings_class = _Embeddings  # subclass hook (ERNIE swaps this)

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = type(self).embeddings_class(cfg)
        self.encoder = _Encoder(cfg)
        self.pooler = _Pooler(cfg)

    @staticmethod
    def _additive_mask(attention_mask):
        """[b, s] 1/0 padding mask → additive [b, 1, 1, s] (shared with
        subclasses so a mask fix covers the family)."""
        if attention_mask is None:
            return None
        return (1.0 - attention_mask[:, None, None, :].astype(
            jnp.float32)) * -1e9

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        """→ (sequence_output [b,s,h], pooled_output [b,h]) — the
        PaddleNLP BertModel return shape."""
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        x = self.encoder(x, self._additive_mask(attention_mask))
        return x, self.pooler(x)


def bert(name_or_config="tiny", **overrides) -> BertModel:
    cfg = (PRESETS[name_or_config] if isinstance(name_or_config, str)
           else name_or_config)
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return BertModel(cfg)
