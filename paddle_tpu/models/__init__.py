"""In-repo model zoo (BASELINE.json configs).

- llama: Llama-2 family (7B/13B/70B + small configs) — flagship
- gpt: GPT/ERNIE-style decoder (13B TP+PP config)
- moe: Mixtral-style mixture-of-experts (expert parallel)
- sdxl_unet: Stable-Diffusion-XL UNet (conv/GroupNorm/attention breadth)
"""

from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel, PRESETS,  # noqa: F401
                    causal_lm_loss, llama)


def __getattr__(name):
    import importlib
    if name in ("gpt", "moe", "sdxl_unet"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
