"""Round-3 functional tail: loss zoo completion + pooling/activation ops.

Reference: python/paddle/nn/functional/{loss,pooling,activation}.py members
not yet covered (SURVEY §2.6 nn row).  Torch-oracle tests in
tests/test_nn_tail3.py.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import random as prandom


def _reduce(out, reduction):
    if reduction == "mean":
        return out.mean()
    if reduction == "sum":
        return out.sum()
    return out


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def soft_margin_loss(input, label, reduction="mean", name=None):
    """Reference: paddle.nn.functional.soft_margin_loss —
    log(1 + exp(-label * input)), in the overflow-stable softplus form."""
    out = jax.nn.softplus(-label * input)
    return _reduce(out, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Reference: multi_margin_loss — hinge over the true class margin."""
    n, c = input.shape
    true = jnp.take_along_axis(input, label[:, None], axis=1)  # (n, 1)
    m = jnp.maximum(0.0, margin - true + input) ** p
    if weight is not None:
        m = m * weight[label][:, None]
    mask = jax.nn.one_hot(label, c, dtype=bool)
    out = jnp.where(mask, 0.0, m).sum(axis=1) / c
    return _reduce(out, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """Reference: multi-label one-versus-all soft margin."""
    out = -(label * jax.nn.log_sigmoid(input)
            + (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        out = out * weight
    out = out.mean(axis=-1)
    return _reduce(out, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function
    if dist is None:
        dist = lambda a, b: jnp.linalg.norm(a - b, axis=-1)
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    if log_input:
        out = jnp.exp(input) - label * input
    else:
        out = input - label * jnp.log(input + epsilon)
    if full:
        # Stirling term for label > 1 (reference/torch semantics)
        stirling = (label * jnp.log(label) - label
                    + 0.5 * jnp.log(2 * math.pi * label))
        out = out + jnp.where(label > 1, stirling, 0.0)
    return _reduce(out, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    var = jnp.clip(variance, epsilon, None)
    out = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
    if full:
        out = out + 0.5 * math.log(2 * math.pi)
    return _reduce(out, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum", name=None):
    """Reference: paddle.nn.functional.sigmoid_focal_loss (RetinaNet)."""
    p = jax.nn.sigmoid(logit)
    ce = -(label * jax.nn.log_sigmoid(logit)
           + (1 - label) * jax.nn.log_sigmoid(-logit))
    p_t = p * label + (1 - p) * (1 - label)
    out = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        out = out * (alpha * label + (1 - alpha) * (1 - label))
    if normalizer is not None:
        out = out / normalizer
    return _reduce(out, reduction)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Reference: paddle dice_loss — input [N, ..., C] probabilities,
    label [N, ..., 1] int class ids."""
    c = input.shape[-1]
    oh = jax.nn.one_hot(jnp.squeeze(label, -1), c, dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = (input * oh).sum(axis=reduce_dims)
    union = input.sum(axis=reduce_dims) + oh.sum(axis=reduce_dims)
    return (1.0 - (2 * inter + epsilon) / (union + epsilon)).mean()


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """Reference: paddle npair_loss (Sohn 2016): softmax CE over the
    anchor·positiveᵀ similarity matrix + L2 on the embeddings."""
    labels = labels.reshape(-1)
    same = (labels[:, None] == labels[None, :]).astype(anchor.dtype)
    tgt = same / same.sum(axis=1, keepdims=True)
    sim = anchor @ positive.T
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -(tgt * logp).sum(axis=1).mean()
    reg = l2_reg * ((anchor ** 2).sum(axis=1).mean()
                    + (positive ** 2).sum(axis=1).mean()) / 2
    return ce + reg


def square_error_cost(input, label, name=None):
    return (input - label) ** 2


def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """Reference: paddle.nn.functional.rnnt_loss (RNA/RNN-T transducer).

    ``logits``: (B, T, U+1, V) joint-network outputs; ``labels``: (B, U)
    int targets.  Log-domain forward DP over the (T, U) lattice via a
    wavefront scan — XLA-friendly (no data-dependent Python loops)."""
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: fastemit_lambda > 0 (FastEmit regularization) is "
            "not implemented — pass 0.0, or regularize emission latency "
            "externally")
    b, t_max, u1, v = logits.shape
    u_max = u1 - 1
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # per (t, u): emit-prob of the next label, and blank prob
    lab = labels.astype(jnp.int32)
    emit = jnp.take_along_axis(
        logp, jnp.pad(lab, ((0, 0), (0, 1)))[:, None, :, None]
        .repeat(t_max, 1), axis=-1)[..., 0]          # (B, T, U+1)
    blank_p = logp[..., blank]                       # (B, T, U+1)
    NEG = -1e30

    # alpha DP: alpha[t, u] = logsumexp(alpha[t-1, u] + blank[t-1, u],
    #                                   alpha[t, u-1] + emit[t, u-1])
    # scan over t; inner associative scan over u per row
    def row_scan(alpha_prev, x):
        blank_prev, emit_row = x     # (B, U+1) each
        # base: coming from the row above (time step t-1)
        base = alpha_prev + blank_prev
        # then cumulative emissions along u:
        # alpha[u] = logsumexp over j<=u of base[j] + sum(emit[j..u-1])
        def u_step(carry, xu):
            base_u, emit_u = xu
            cur = jnp.logaddexp(carry + emit_u, base_u)
            return cur, cur
        e_shift = jnp.concatenate([jnp.full((b, 1), NEG), emit_row[:, :-1]],
                                  axis=1)
        _, rows = jax.lax.scan(
            u_step, jnp.full((b,), NEG),
            (base.T, e_shift.T))
        alpha = rows.T
        return alpha, alpha

    alpha0 = jnp.full((b, u1), NEG).at[:, 0].set(0.0)
    # u-cumulation for t=0 row: only emits
    def u0_step(carry, emit_u):
        cur = carry + emit_u
        return cur, cur
    _, a0rows = jax.lax.scan(u0_step, jnp.zeros((b,)),
                             emit[:, 0, :-1].T)
    alpha_t0 = jnp.concatenate([jnp.zeros((b, 1)), a0rows.T], axis=1)

    def t_step(alpha_prev, x):
        blank_prev, emit_row = x
        return row_scan(alpha_prev, (blank_prev, emit_row))

    _, alphas = jax.lax.scan(
        t_step, alpha_t0,
        (blank_p[:, :-1].transpose(1, 0, 2), emit[:, 1:].transpose(1, 0, 2)))
    alphas = jnp.concatenate([alpha_t0[None], alphas], axis=0)  # (T, B, U+1)

    t_idx = (logit_lengths - 1).astype(jnp.int32)
    u_idx = label_lengths.astype(jnp.int32)
    a_final = alphas[t_idx, jnp.arange(b), u_idx]
    blank_final = blank_p[jnp.arange(b), t_idx, u_idx]
    nll = -(a_final + blank_final)
    if reduction == "mean":
        return nll.mean()
    if reduction == "sum":
        return nll.sum()
    return nll


# ---------------------------------------------------------------------------
# activations / pooling
# ---------------------------------------------------------------------------

def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    """Reference: paddle.nn.functional.gumbel_softmax."""
    key = prandom.next_key("gumbel")
    g = jax.random.gumbel(key, x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        # straight-through: one-hot forward, softmax gradient
        if axis in (-1, x.ndim - 1):
            hard_y = jax.nn.one_hot(jnp.argmax(y, axis=axis),
                                    y.shape[axis], dtype=y.dtype)
        else:
            y_max = jnp.max(y, axis=axis, keepdims=True)
            hard_y = (y == y_max).astype(y.dtype)
        return jax.lax.stop_gradient(hard_y - y) + y
    return y


def _sum_pool(x, window, strides, pad_spatial, ceil_mode):
    """Sum-reduce spatial windows over the trailing len(window) dims."""
    ndim = x.ndim
    k = len(window)
    full_window = (1,) * (ndim - k) + tuple(window)
    full_strides = (1,) * (ndim - k) + tuple(strides)
    pads = [(0, 0)] * (ndim - k)
    for i in range(k):
        lo = pad_spatial[i]
        hi = pad_spatial[i]
        if ceil_mode:
            n = x.shape[ndim - k + i] + 2 * pad_spatial[i]
            rem = (n - window[i]) % strides[i]
            if rem:
                hi += strides[i] - rem
        pads.append((lo, hi))
    return jax.lax.reduce_window(x, 0.0, jax.lax.add, full_window,
                                 full_strides, pads)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """Reference: paddle.nn.functional.lp_pool1d —
    (sum x^p over window)^(1/p)."""
    p = float(norm_type)
    s = kernel_size if stride is None else stride
    summed = _sum_pool(x ** p, (kernel_size,), (s,), (padding,), ceil_mode)
    return summed ** (1.0 / p)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    ks = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
          else tuple(kernel_size))
    st = (ks if stride is None else
          ((stride, stride) if isinstance(stride, int) else tuple(stride)))
    pd = ((padding, padding) if isinstance(padding, int)
          else tuple(padding))
    summed = _sum_pool(x ** p, ks, st, pd, ceil_mode)
    return summed ** (1.0 / p)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Reference: max_unpool1d — inverse of max_pool1d w/ return_mask."""
    from .functional import max_unpool2d
    out = max_unpool2d(x[..., None, :], indices[..., None, :],
                       (1, kernel_size),
                       stride=(1, stride or kernel_size),
                       padding=(0, padding) if padding else 0,
                       output_size=(None if output_size is None
                                    else (1, output_size[-1])))
    return out[..., 0, :]


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Reference: max_unpool3d — scatter back by flat DHW indices."""
    b, c, d, h, w = x.shape
    if output_size is None:
        ks = ((kernel_size,) * 3 if isinstance(kernel_size, int)
              else tuple(kernel_size))
        st = (ks if stride is None else
              ((stride,) * 3 if isinstance(stride, int) else tuple(stride)))
        pd = ((padding,) * 3 if isinstance(padding, int)
              else tuple(padding))
        od = (d - 1) * st[0] - 2 * pd[0] + ks[0]
        oh = (h - 1) * st[1] - 2 * pd[1] + ks[1]
        ow = (w - 1) * st[2] - 2 * pd[2] + ks[2]
    else:
        od, oh, ow = output_size[-3:]
    flat = jnp.zeros((b, c, od * oh * ow), x.dtype)
    out = flat.at[jnp.arange(b)[:, None, None], jnp.arange(c)[None, :, None],
                  indices.reshape(b, c, -1)].set(x.reshape(b, c, -1))
    return out.reshape(b, c, od, oh, ow)


def fractional_max_pool2d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """Reference: paddle.nn.functional.fractional_max_pool2d.

    Pooling regions follow the fractional scheme (Graham 2014) with a
    single random u per call (paddle's ``random_u``): region boundaries
    alpha = in/out, start_i = ceil(alpha*(i+u)) - ceil(alpha*u)."""
    b, c, h, w = x.shape
    oh, ow = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size)[-2:])
    if random_u is None:
        key = prandom.next_key("fractional_pool")
        u = float(jax.random.uniform(key, ()))
    else:
        u = float(random_u)

    def edges(n_in, n_out):
        import numpy as np
        alpha = n_in / n_out
        idx = np.arange(n_out + 1)
        pts = np.ceil(alpha * (idx + u)).astype(int) - int(np.ceil(alpha * u))
        pts[-1] = n_in
        return pts

    eh, ew = edges(h, oh), edges(w, ow)
    rows = []
    for i in range(oh):
        cols = []
        for j in range(ow):
            win = x[:, :, eh[i]:eh[i + 1], ew[j]:ew[j + 1]]
            cols.append(win.max(axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    out = jnp.stack(rows, axis=-2)
    if return_mask:
        # flat HxW argmax index per output cell
        masks = []
        for i in range(oh):
            mcols = []
            for j in range(ow):
                win = x[:, :, eh[i]:eh[i + 1], ew[j]:ew[j + 1]]
                wh = win.shape[2]
                ww = win.shape[3]
                am = jnp.argmax(win.reshape(b, c, -1), axis=-1)
                r = am // ww + eh[i]
                cc = am % ww + ew[j]
                mcols.append(r * w + cc)
            masks.append(jnp.stack(mcols, axis=-1))
        return out, jnp.stack(masks, axis=-2)
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """Reference: fractional_max_pool3d — depth added to the 2-D scheme."""
    b, c, d, h, w = x.shape
    od, oh, ow = ((output_size,) * 3 if isinstance(output_size, int)
                  else tuple(output_size)[-3:])
    if random_u is None:
        key = prandom.next_key("fractional_pool")
        u = float(jax.random.uniform(key, ()))
    else:
        u = float(random_u)

    def edges(n_in, n_out):
        import numpy as np
        alpha = n_in / n_out
        idx = np.arange(n_out + 1)
        pts = np.ceil(alpha * (idx + u)).astype(int) - int(np.ceil(alpha * u))
        pts[-1] = n_in
        return pts

    ed, eh, ew = edges(d, od), edges(h, oh), edges(w, ow)
    out = jnp.stack([
        jnp.stack([
            jnp.stack([
                x[:, :, ed[a]:ed[a + 1], eh[i]:eh[i + 1],
                  ew[j]:ew[j + 1]].max(axis=(2, 3, 4))
                for j in range(ow)], axis=-1)
            for i in range(oh)], axis=-2)
        for a in range(od)], axis=-3)
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool3d(return_mask=True): use the 2-D variant "
            "or max_pool3d(return_mask=True)")
    return out
