"""Layer-zoo breadth batch (reference: python/paddle/nn/layer/{activation,
common,pooling,norm}.py — the remaining paddle.nn classes).

Everything here is a thin Layer over the functional op (the kernels are
jnp/lax, fused by XLA); classes exist for API/porting parity and for
``Sequential`` composition.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import functional as F
from .layer import Layer
from .layer import ParamAttr  # noqa: F401  (re-export convenience)

__all__ = [
    "Pad1D", "Pad3D", "ZeroPad2D", "ChannelShuffle", "UpsamplingBilinear2D",
    "UpsamplingNearest2D", "Fold", "Unfold", "PairwiseDistance", "Bilinear",
    "Unflatten", "Dropout3D", "AlphaDropout", "FeatureAlphaDropout",
    "LocalResponseNorm", "SyncBatchNorm", "AdaptiveMaxPool1D", "MaxUnPool2D",
    "Softmax2D", "GLU", "SELU", "CELU", "Softshrink", "Hardshrink",
    "Tanhshrink", "ThresholdedReLU", "LogSigmoid",
]


class _Activation(Layer):
    _fn = None

    def forward(self, x):
        return type(self)._fn(x)


class SELU(_Activation):
    _fn = staticmethod(F.selu)


class CELU(Layer):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class GLU(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class Softshrink(Layer):
    def __init__(self, threshold=0.5):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Tanhshrink(_Activation):
    _fn = staticmethod(F.tanhshrink)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold, self.value)


class LogSigmoid(_Activation):
    _fn = staticmethod(F.log_sigmoid)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input (reference
    paddle.nn.Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL"):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW"):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW"):
        super().__init__()
        self.padding, self.data_format = padding, data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW"):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size,
                             scale_factor=self.scale_factor,
                             mode="bilinear", align_corners=True,
                             data_format=self.data_format)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size,
                             scale_factor=self.scale_factor,
                             mode="nearest", data_format=self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1):
        super().__init__()
        self.a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.a)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.a = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.a)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Bilinear(Layer):
    """out = x1 @ W @ x2 + b per output feature (reference
    paddle.nn.Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from . import initializer as I
        k = 1.0 / (in1_features ** 0.5)
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            attr=weight_attr, default_initializer=I.Uniform(-k, k))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_features,), attr=bias_attr,
            default_initializer=I.Uniform(-k, k)))

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unflatten(Layer):
    def __init__(self, axis, shape):
        super().__init__()
        self.axis, self.shape = axis, tuple(shape)

    def forward(self, x):
        from ..ops.more import unflatten
        return unflatten(x, self.axis, self.shape)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW"):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, self.training)


class FeatureAlphaDropout(AlphaDropout):
    """Channel-wise alpha dropout; approximated by element alpha dropout
    on TPU (documented deviation — the self-normalizing statistics are
    per-element either way)."""


class LocalResponseNorm(Layer):
    def __init__(self, size=5, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW"):
        super().__init__()
        self.a = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.a)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False):
        super().__init__()
        if return_mask:
            raise NotImplementedError("return_mask: use F.max_pool indices")
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW"):
        super().__init__()
        self.a = (kernel_size, stride, padding)
        self.data_format = data_format

    def forward(self, x, indices, output_size=None):
        return F.max_unpool2d(x, indices, *self.a, output_size=output_size,
                              data_format=self.data_format)


class SyncBatchNorm(Layer):
    """Cross-replica batch norm (reference: paddle.nn.SyncBatchNorm over
    NCCL all-reduce).

    Under single-controller SPMD the batch is one global array: plain
    BatchNorm statistics computed on it ARE the synced statistics (XLA
    inserts the cross-device reductions for the sharded batch dim), so
    this delegates to BatchNorm2D and exists for porting parity.
    ``convert_sync_batchnorm`` mirrors the reference helper.
    """

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        from .layers_common import BatchNorm2D
        self._bn = BatchNorm2D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        return self._bn(x)

    @classmethod
    def convert_sync_batchnorm(cls, layer: Layer) -> Layer:
        """No-op structural walk (stats are already global under SPMD);
        returns the layer for reference-code compatibility."""
        return layer
