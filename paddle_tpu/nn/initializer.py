"""Parameter initializers (``paddle.nn.initializer`` parity).

Reference: python/paddle/nn/initializer/*.py.  Paddle initializers mutate a
created parameter in place; here an initializer is a pure callable
``init(key, shape, dtype) -> jax.Array`` so parameter creation stays
functional and reproducible under a single step key.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


class Initializer:
    def __call__(self, key, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, key, shape, dtype):
        return (self.mean + self.std * jax.random.normal(key, shape, dtype=jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, key, shape, dtype):
        x = jax.random.truncated_normal(key, self.a, self.b, shape, dtype=jnp.float32)
        return (self.mean + self.std * x).astype(dtype)


def _fans(shape):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = math.prod(shape[2:]) if len(shape) > 2 else 1
    # Linear weights in this framework are (in_features, out_features);
    # conv weights are (out, in, *k) as in the reference.
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    else:
        fan_in, fan_out = shape[1] * receptive, shape[0] * receptive
    return fan_in, fan_out


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, key, shape, dtype):
        fi, fo = _fans(shape)
        fi, fo = self.fan_in or fi, self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, key, shape, dtype):
        fi, fo = _fans(shape)
        fi, fo = self.fan_in or fi, self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, key, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, key, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        std = gain / math.sqrt(fi)
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dtype)


# paddle default initializers: XavierUniform-ish for weights, zeros for bias.
def default_weight_init():
    if _GLOBAL_INIT[0] is not None:
        return _GLOBAL_INIT[0]
    return XavierUniform()


def default_bias_init():
    if _GLOBAL_INIT[1] is not None:
        return _GLOBAL_INIT[1]
    return Constant(0.0)


_GLOBAL_INIT = [None, None]   # (weight_init, bias_init) — see
                              # set_global_initializer below


class Orthogonal(Initializer):
    """Reference: paddle.nn.initializer.Orthogonal (QR of a gaussian).

    QR runs on a (max, min)-shaped gaussian — O(max·min²), not the naive
    (max, max) square which would OOM on lopsided shapes like vocab
    embeddings."""

    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal requires >=2 dims")
        rows = shape[0]
        cols = 1
        for s in shape[1:]:
            cols *= s
        big, small = max(rows, cols), min(rows, cols)
        a = jax.random.normal(key, (big, small), jnp.float32)
        q, r = jnp.linalg.qr(a)          # q: (big, small), semi-orthogonal
        q = q * jnp.sign(jnp.diagonal(r))  # unique, uniform distribution
        if rows < cols:
            q = q.T
        return (self.gain * q).reshape(shape).astype(dtype)


class Assign(Initializer):
    """Reference: paddle.nn.initializer.Assign (constant array init)."""

    def __init__(self, value):
        self.value = value

    def __call__(self, key, shape, dtype):
        v = jnp.asarray(self.value, dtype=dtype)
        if tuple(v.shape) != tuple(shape):
            raise ValueError(f"Assign value shape {v.shape} != {shape}")
        return v


class Dirac(Initializer):
    """Reference: paddle.nn.initializer.Dirac (identity-preserving convs)."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, key, shape, dtype):
        if len(shape) < 3:
            raise ValueError("Dirac requires conv-shaped (>=3d) params")
        out_c, in_c = shape[0], shape[1]
        if out_c % self.groups:
            raise ValueError(
                f"Dirac: out_channels ({out_c}) must be divisible by "
                f"groups ({self.groups})")
        w = jnp.zeros(shape, dtype)
        centers = tuple(s // 2 for s in shape[2:])
        og = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(og, in_c)):
                idx = (g * og + i, i) + centers
                w = w.at[idx].set(1.0)
        return w


def calculate_gain(nonlinearity, param=None):
    """Reference: paddle.nn.initializer.calculate_gain."""
    gains = {"linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
             "conv_transpose1d": 1.0, "conv_transpose2d": 1.0,
             "conv_transpose3d": 1.0, "sigmoid": 1.0,
             "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "selu": 3.0 / 4.0}
    if nonlinearity in gains:
        return gains[nonlinearity]
    if nonlinearity == "leaky_relu":
        slope = 0.01 if param is None else float(param)
        return math.sqrt(2.0 / (1 + slope ** 2))
    raise ValueError(f"unknown nonlinearity {nonlinearity!r}")


class Bilinear(Initializer):
    """Reference: paddle.nn.initializer.Bilinear — bilinear-upsampling
    kernel for transposed convolutions [C_out, C_in, k, k]."""

    def __call__(self, key, shape, dtype):
        import numpy as np
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D shape")
        k = shape[3]
        factor = (k + 1) // 2
        center = factor - 1.0 if k % 2 == 1 else factor - 0.5
        og = np.ogrid[:k, :k]
        filt = ((1 - np.abs(og[0] - center) / factor)
                * (1 - np.abs(og[1] - center) / factor))
        # place the kernel on the diagonal channel pairs
        w = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            w[i, i % shape[1]] = filt
        return jnp.asarray(w, dtype)


def set_global_initializer(weight_init, bias_init=None):
    """Reference: paddle.nn.initializer.set_global_initializer — default
    initializers used when a layer gives none."""
    _GLOBAL_INIT[0] = weight_init
    _GLOBAL_INIT[1] = bias_init


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
