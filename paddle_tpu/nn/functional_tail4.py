"""Round-4 nn.functional tail: 3-D pooling, 1-D/3-D transpose convs,
sequence/loss ops, ArcFace margin CE, block-sparse attention, beam-search
gather_tree, hierarchical sigmoid.

Reference: python/paddle/nn/functional/{pooling,conv,loss,common,extension}.py
(SURVEY §2.6 layers & functional row).  Oracle tests in
tests/test_nn_tail4.py.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# 3-D pooling
# ---------------------------------------------------------------------------

def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def avg_pool3d(x, kernel_size, stride=None, padding=0, data_format="NCDHW"):
    k = _triple(kernel_size)
    s = k if stride is None else _triple(stride)
    p = _triple(padding)
    if data_format == "NCDHW":
        window = (1, 1, *k)
        strides = (1, 1, *s)
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]))
    else:
        window = (1, *k, 1)
        strides = (1, *s, 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]), (0, 0))
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    count = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                  window, strides, pads)
    return summed / count


def max_pool3d(x, kernel_size, stride=None, padding=0, data_format="NCDHW"):
    k = _triple(kernel_size)
    s = k if stride is None else _triple(stride)
    p = _triple(padding)
    if data_format == "NCDHW":
        window = (1, 1, *k)
        strides = (1, 1, *s)
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]))
    else:
        window = (1, *k, 1)
        strides = (1, *s, 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]), (0, 0))
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides,
                                 pads)


# ---------------------------------------------------------------------------
# adaptive pooling (1-D / 3-D) — generic exact per-axis bucketing.
# Per-axis sequential reduction is exact: within one output cell every
# axis's bucket size is fixed, so mean-of-means equals the true mean.
# ---------------------------------------------------------------------------

def _adaptive_pool_axes(x, out_sizes, axes, reduce_fn):
    for axis, out in zip(axes, out_sizes):
        n = x.shape[axis]
        pieces = [reduce_fn(
            jax.lax.slice_in_dim(x, int(i * n / out),
                                 int(-(-((i + 1) * n) // out)), axis=axis),
            axis=axis, keepdims=True) for i in range(out)]
        x = jnp.concatenate(pieces, axis=axis)
    return x


def adaptive_avg_pool1d(x, output_size):
    out = output_size if isinstance(output_size, int) else output_size[0]
    return _adaptive_pool_axes(x, (out,), (2,), jnp.mean)


def adaptive_max_pool1d(x, output_size, return_mask=False):
    out = output_size if isinstance(output_size, int) else output_size[0]
    y = _adaptive_pool_axes(x, (out,), (2,), jnp.max)
    if return_mask:
        # index (within the full L axis) of each window's max
        n = x.shape[2]
        idx = []
        for i in range(out):
            a = int(i * n / out)
            b = int(-(-((i + 1) * n) // out))
            seg = jax.lax.slice_in_dim(x, a, b, axis=2)
            idx.append(jnp.argmax(seg, axis=2, keepdims=True) + a)
        return y, jnp.concatenate(idx, axis=2)
    return y


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    out = _triple(output_size)
    axes = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    return _adaptive_pool_axes(x, out, axes, jnp.mean)


def adaptive_max_pool3d(x, output_size, data_format="NCDHW"):
    out = _triple(output_size)
    axes = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    return _adaptive_pool_axes(x, out, axes, jnp.max)


# ---------------------------------------------------------------------------
# 1-D / 3-D transpose convolution (shared n-d core; same lhs_dilation
# lowering as conv2d_transpose — MXU-friendly, no scatter)
# ---------------------------------------------------------------------------

def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, specs, channel_last):
    from .functional import _conv_dtypes, _conv_pet
    x, weight = _conv_dtypes(x, weight)
    nd = len(weight.shape) - 2
    as_nd = lambda v: (v,) * nd if isinstance(v, int) else tuple(v)
    s, d = as_nd(stride), as_nd(dilation)
    p, op = as_nd(padding), as_nd(output_padding)
    ks = weight.shape[-nd:]
    ek = [(k - 1) * dd + 1 for k, dd in zip(ks, d)]
    pad = [(e - 1 - pp, e - 1 - pp + o) for e, pp, o in zip(ek, p, op)]
    axes = tuple(range(2, 2 + nd))
    w = jnp.flip(weight, axis=axes)  # (I, O/g, *k) → rotate spatial
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        i, og = w.shape[0], w.shape[1]
        w = w.reshape(groups, i // groups, og, *ks).swapaxes(1, 2) \
             .reshape(groups * og, i // groups, *ks)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, specs)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=pad, lhs_dilation=s,
        rhs_dilation=d, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=_conv_pet(x.dtype)).astype(x.dtype)
    if bias is not None:
        shape = [1, -1] + [1] * nd if not channel_last else \
            [1] + [1] * nd + [-1]
        out = out + bias.reshape(shape).astype(out.dtype)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL"):
    """Weight layout (in_c, out_c/groups, k) — the reference's
    Conv1DTranspose convention."""
    specs = ("NCH", "OIH", "NCH") if data_format == "NCL" \
        else ("NHC", "OIH", "NHC")
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, specs,
                              data_format != "NCL")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW"):
    specs = ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW" \
        else ("NDHWC", "OIDHW", "NDHWC")
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, specs,
                              data_format != "NCDHW")


# ---------------------------------------------------------------------------
# losses / label utilities
# ---------------------------------------------------------------------------

def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """Reference: F.label_smooth — (1-ε)·y + ε·prior (uniform default)."""
    label = jnp.asarray(label)
    if prior_dist is not None:
        prior = jnp.asarray(prior_dist)
    else:
        prior = 1.0 / label.shape[-1]
    return (1.0 - epsilon) * label + epsilon * prior


def log_loss(input, label, epsilon=1e-4, name=None):
    """Reference: F.log_loss — per-element binary log loss on
    probabilities."""
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    return -y * jnp.log(x + epsilon) - (1.0 - y) * jnp.log(1.0 - x + epsilon)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Reference: F.sequence_mask — mask[..., j] = j < x[...]."""
    from ..core import convert_dtype
    x = jnp.asarray(x)
    if maxlen is None:
        raise ValueError(
            "sequence_mask: maxlen must be given under jit (output shape "
            "would otherwise depend on data — XLA needs static shapes)")
    r = jnp.arange(int(maxlen))
    mask = r[None, :] < x.reshape(-1, 1)
    return mask.reshape(*x.shape, int(maxlen)).astype(convert_dtype(dtype))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """Reference: F.temporal_shift (TSM) — shift the first channel fold
    backward in time, the second fold forward, zero-padding boundaries."""
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    nt, c, h, w = x.shape
    v = x.reshape(-1, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    pad_t = jnp.zeros_like(v[:, :1])
    prev = jnp.concatenate([pad_t, v[:, :-1]], axis=1)   # frame t-1
    nxt = jnp.concatenate([v[:, 1:], pad_t], axis=1)     # frame t+1
    out = jnp.concatenate([prev[:, :, :c1], nxt[:, :, c1:c2], v[:, :, c2:]],
                          axis=2).reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def gather_tree(ids, parents):
    """Reference: F.gather_tree — walk beam-search parent pointers from
    the last step backward so each beam holds its full token path.
    Shapes (T, B, beam); a reverse lax.scan carries the beam indices."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    T, B, K = ids.shape
    binx = jnp.arange(B)[:, None]

    def step(beam_at, inputs):
        ids_t, parents_t = inputs
        out_t = ids_t[binx, beam_at]
        beam_prev = parents_t[binx, beam_at]
        return beam_prev, out_t

    init = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
    _, outs = jax.lax.scan(step, init, (ids, parents), reverse=True)
    return outs


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Reference: F.hsigmoid_loss — hierarchical sigmoid over a complete
    binary tree (default) or a custom path table.

    Default-tree node codes follow the reference's SimpleCode: for class c,
    ``code = c + num_classes``; walking bits from the lowest, the internal
    node visited at bit i is ``(code >> (i+1)) - 1`` and the branch taken
    is ``(code >> i) & 1``.  Bits above the code's MSB are masked out.
    """
    x = jnp.asarray(input)                      # (B, F)
    lab = jnp.asarray(label).reshape(-1)        # (B,)
    w = jnp.asarray(weight)                     # (num_classes-1, F) default
    if path_table is not None:
        pt = jnp.asarray(path_table)            # (B, L) node ids, -1 pad
        pc = jnp.asarray(path_code).astype(jnp.float32)  # (B, L) bits
        valid = (pt >= 0)
        idx = jnp.where(valid, pt, 0)
    else:
        code = lab + num_classes                # (B,)
        maxL = max(1, int(math.ceil(math.log2(2 * num_classes - 1))))
        bits = jnp.arange(maxL)                 # (L,)
        idx = (code[:, None] >> (bits[None, :] + 1)) - 1
        pc = ((code[:, None] >> bits[None, :]) & 1).astype(jnp.float32)
        # bit i participates iff the node index is a real internal node,
        # i.e. code has a set bit above position i
        valid = (code[:, None] >> (bits[None, :] + 1)) > 0
        idx = jnp.where(valid, idx, 0)
    wg = w[idx]                                 # (B, L, F)
    pre = jnp.einsum("bf,blf->bl", x, wg)
    if bias is not None:
        b = jnp.asarray(bias).reshape(-1)
        pre = pre + b[idx]
    # BCE with logits against the branch bit, summed over the valid path
    per_bit = jnp.maximum(pre, 0) - pre * pc + jnp.log1p(jnp.exp(-jnp.abs(pre)))
    loss = jnp.sum(jnp.where(valid, per_bit, 0.0), axis=1, keepdims=True)
    return loss


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """Reference: F.margin_cross_entropy — combined-margin (ArcFace-family)
    softmax CE.  ``logits`` are cosine similarities; the target class gets
    cos(m1·θ + m2) - m3 before scaling.

    The reference's class-parallel mode shards classes over a process
    group; here shard the class axis with mp_layers.ParallelCrossEntropy
    instead (group must be None).
    """
    if group is not None and group is not False:
        raise NotImplementedError(
            "margin_cross_entropy(group=...): class-parallel margin CE is "
            "expressed via mesh sharding — see distributed/mp_layers.py "
            "ParallelCrossEntropy (SURVEY §2.5)")
    cos = jnp.asarray(logits)
    lab = jnp.asarray(label).reshape(-1)
    onehot = jax.nn.one_hot(lab, cos.shape[-1], dtype=cos.dtype)
    theta = jnp.arccos(jnp.clip(cos, -1.0 + 1e-7, 1.0 - 1e-7))
    target = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = jnp.where(onehot > 0, target, cos) * scale
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.sum(onehot * logp, axis=-1, keepdims=True)
    if reduction == "mean":
        loss = jnp.mean(loss)
    elif reduction == "sum":
        loss = jnp.sum(loss)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Reference: F.class_center_sample — keep every positive class center
    and fill to ``num_samples`` with uniformly sampled negatives; returns
    (remapped_label, sampled_class_ids) with ids sorted ascending."""
    from ..core import random as prandom
    lab = jnp.asarray(label).reshape(-1)
    pos = jnp.zeros((num_classes,), jnp.float32).at[lab].set(1.0)
    noise = jax.random.uniform(prandom.next_key("class_center_sample"),
                               (num_classes,))
    # positives rank above any negative; negatives ordered by noise
    score = pos * 2.0 + noise
    _, picked = jax.lax.top_k(score, num_samples)
    sampled = jnp.sort(picked)
    remapped = jnp.searchsorted(sampled, lab)
    return remapped, sampled


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Reference: F.sparse_attention — attention restricted to a per-row
    CSR column set.  Shapes: q/k/v (B, H, M, D), offset (B, H, M+1),
    columns (B, H, nnz).

    TPU formulation: expand each nnz slot to its row id (searchsorted over
    the offset vector — static shapes), gather k/v at the listed columns,
    and do a segment-softmax over slots.  No dense M×M score matrix is
    materialised; cost is O(nnz·D)."""
    q = jnp.asarray(query)
    k = jnp.asarray(key)
    v = jnp.asarray(value)
    off = jnp.asarray(sparse_csr_offset).astype(jnp.int32)
    cols = jnp.asarray(sparse_csr_columns).astype(jnp.int32)
    B, H, M, D = q.shape
    nnz = cols.shape[-1]
    inv_sqrt_d = 1.0 / math.sqrt(D)

    def one_head(qh, kh, vh, offh, colh):
        slot = jnp.arange(nnz)
        row = jnp.searchsorted(offh, slot, side="right") - 1  # (nnz,)
        row = jnp.clip(row, 0, M - 1)
        live = slot < offh[-1]
        scores = jnp.sum(qh[row] * kh[colh], axis=-1) * inv_sqrt_d
        scores = jnp.where(live, scores, -jnp.inf)
        mx = jax.ops.segment_max(scores, row, num_segments=M)
        mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
        ex = jnp.where(live, jnp.exp(scores - mx[row]), 0.0)
        den = jax.ops.segment_sum(ex, row, num_segments=M)
        p = ex / jnp.maximum(den[row], 1e-20)
        out = jax.ops.segment_sum(p[:, None] * vh[colh], row,
                                  num_segments=M)
        return out.astype(qh.dtype)

    flat = lambda t: t.reshape(B * H, *t.shape[2:])
    out = jax.vmap(one_head)(flat(q), flat(k), flat(v), flat(off),
                             flat(cols))
    return out.reshape(B, H, M, D)


# ---------------------------------------------------------------------------
# inplace-suffix aliases (value-returning; see ops/tail3.py deviation note)
# ---------------------------------------------------------------------------

def relu_(x, name=None):
    return jax.nn.relu(jnp.asarray(x))


def elu_(x, alpha=1.0, name=None):
    return jax.nn.elu(jnp.asarray(x), alpha)


def softmax_(x, axis=-1, name=None):
    return jax.nn.softmax(jnp.asarray(x), axis=axis)
