"""Functional ops (``paddle.nn.functional`` parity).

Reference: python/paddle/nn/functional/*.py.  Everything here is a pure jnp
function; the hot ops (attention, rms_norm, rope) dispatch to Pallas TPU
kernels when available (paddle_tpu.ops.pallas), mirroring how the reference
routes to fused CUDA kernels (paddle/phi/kernels/fusion/gpu/), with an XLA
fallback that is always numerically authoritative.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import convert_dtype
from ..core import random as prandom

# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

relu = jax.nn.relu
relu6 = jax.nn.relu6
sigmoid = jax.nn.sigmoid
tanh = jnp.tanh
silu = jax.nn.silu
swish = jax.nn.silu
elu = jax.nn.elu
celu = jax.nn.celu
selu = jax.nn.selu
softplus = jax.nn.softplus
log_sigmoid = jax.nn.log_sigmoid
hardswish = jax.nn.hard_swish
leaky_relu = jax.nn.leaky_relu
mish = jax.nn.mish


def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * sigmoid(b)


def swiglu(x, y=None):
    """Reference: paddle.incubate.nn.functional.swiglu (fused in phi)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return silu(x) * y


# ---------------------------------------------------------------------------
# linear / embedding / dropout
# ---------------------------------------------------------------------------

def linear(x, weight, bias=None):
    """Weight layout is (in_features, out_features), as in the reference."""
    y = x @ weight
    if bias is not None:
        y = y + bias
    return y


def embedding(ids, weight, padding_idx=None):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return out


def one_hot(x, num_classes, dtype=jnp.float32):
    return jax.nn.one_hot(x, num_classes, dtype=dtype)


_warned_const_dropout = [False]


def dropout(x, p=0.5, training=True, mode="upscale_in_train", rng_key=None):
    if not training or p == 0.0:
        return x
    if rng_key is None and not prandom.in_rng_scope() and \
            isinstance(x, jax.core.Tracer) and not _warned_const_dropout[0]:
        import warnings
        warnings.warn(
            "dropout traced under jit without an RNG scope: the mask will be "
            "CONSTANT across calls. Use jit.TrainStep / functional_call(..., "
            "rngs=key), or pass rng_key explicitly.", stacklevel=2)
        _warned_const_dropout[0] = True
    key = rng_key if rng_key is not None else prandom.dropout_key()
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(x.ndim - (len(normalized_shape) if normalized_shape else 1), x.ndim))
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=axes, keepdims=True)
    var = jnp.square(xf - mean).mean(axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def rms_norm(x, weight=None, epsilon=1e-6, use_pallas=True):
    """Reference: phi RmsNormKernel (paddle/phi/kernels/fusion/gpu)."""
    from ..ops import dispatch
    impl = dispatch.get("rms_norm") if use_pallas else None
    if impl is not None:
        return impl(x, weight, epsilon)
    xf = x.astype(jnp.float32)
    var = jnp.square(xf).mean(axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    return out.astype(x.dtype)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    g = x.reshape(n, num_groups, c // num_groups, *spatial).astype(jnp.float32)
    axes = tuple(range(2, g.ndim))
    mean = g.mean(axis=axes, keepdims=True)
    var = jnp.square(g - mean).mean(axis=axes, keepdims=True)
    g = (g - mean) * jax.lax.rsqrt(var + epsilon)
    out = g.reshape(n, c, *spatial)
    if weight is not None:
        out = out * weight.reshape(1, c, *([1] * len(spatial)))
    if bias is not None:
        out = out + bias.reshape(1, c, *([1] * len(spatial)))
    out = out.astype(x.dtype)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    caxis = 1 if data_format == "NCHW" else -1
    shape = [1] * x.ndim
    shape[caxis] = x.shape[caxis]
    axes = tuple(i for i in range(x.ndim) if i != (caxis % x.ndim))
    if training:
        mean = x.mean(axis=axes)
        var = x.var(axis=axes)
    else:
        mean, var = running_mean, running_var
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# rotary embedding (reference: fused_rotary_position_embedding / FusedRopeKernel)
# ---------------------------------------------------------------------------

def rope_cos_sin(seq_len, head_dim, base=10000.0, dtype=jnp.float32, position_ids=None):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    pos = jnp.arange(seq_len, dtype=jnp.float32) if position_ids is None else position_ids.astype(jnp.float32)
    freqs = jnp.einsum("...s,d->...sd", pos, inv_freq)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def _rotate_every_two_layout(x):
    # GPT-J / non-NeoX style: pairs are (even, odd) interleaved
    x1, x2 = x[..., 0::2], x[..., 1::2]
    return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)


def _rotate_every_two_mm(x):
    """Interleaved rotation as a {0, ±1} matmul — same rationale and
    precision note as _rotate_half_mm: R[2i+1, 2i] = −1, R[2i, 2i+1] = 1."""
    d = x.shape[-1]
    import numpy as _np
    r = _np.zeros((d, d), _np.float32)
    idx = _np.arange(0, d, 2)
    r[idx + 1, idx] = -1.0
    r[idx, idx + 1] = 1.0
    return jax.lax.dot_general(
        x, jnp.asarray(r, x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)


def _rotate_every_two(x):
    return _rotate_every_two_mm(x) if _rope_impl() == "matmul" \
        else _rotate_every_two_layout(x)


def _rotate_half_mm(x):
    """rotate_half as one tiny (d, d) matmul: y = x @ R with
    R[k+d/2, k] = −1, R[k−d/2, k] = 1.  Step attribution (BENCH.md
    §attribution) measured the split/concat/negate formulation at 29 ms
    /step (13%) on the d64 headline — pure layout traffic; the matmul
    form rides the MXU at ~0.5 GFLOP/step instead and fuses with the
    surrounding cos/sin elementwise."""
    d = x.shape[-1]
    half = d // 2
    import numpy as _np
    r = _np.zeros((d, d), _np.float32)
    r[half:, :half] = -_np.eye(half, dtype=_np.float32)
    r[:half, half:] = _np.eye(half, dtype=_np.float32)
    # precision=HIGHEST: the default TPU matmul rounds f32 operands to
    # bf16, which would silently change f32-model rope numerics; with R
    # in {0, ±1} the highest-precision product is exact and still tiny
    return jax.lax.dot_general(
        x, jnp.asarray(r, x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)


_ROPE_IMPL = None  # resolved lazily from PDTPU_ROPE_IMPL (matmul|layout)


def _rope_impl():
    global _ROPE_IMPL
    if _ROPE_IMPL is None:
        import os as _os
        _ROPE_IMPL = _os.environ.get("PDTPU_ROPE_IMPL", "matmul")
    return _ROPE_IMPL


def _rope_rotate_half(x):
    return _rotate_half_mm(x) if _rope_impl() == "matmul" else _rotate_half(x)


def apply_rotary_pos_emb(q, k, cos, sin, interleaved=False):
    """q/k: [batch, seq, heads, head_dim]; cos/sin: [seq, head_dim] or
    [batch, seq, head_dim] (explicit position_ids).  ``interleaved`` selects
    GPT-J pairing (reference: use_neox_rotary_style=False)."""
    if cos.ndim == 2:    # (s, d) -> (1, s, 1, d)
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    elif cos.ndim == 3:  # (b, s, d) -> (b, s, 1, d)
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    rot = _rotate_every_two if interleaved else _rope_rotate_half
    q_out = q * cos + rot(q) * sin
    k_out = k * cos + rot(k) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)


def fused_rotary_position_embedding(q, k, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding parity.

    NeoX style pairs dimension i with i+d/2 (half-split); non-NeoX pairs
    (2i, 2i+1) interleaved, with frequencies repeated per pair.
    """
    d = q.shape[-1]
    if cos is None or sin is None:
        if use_neox_rotary_style:
            cos, sin = rope_cos_sin(q.shape[1], d, dtype=q.dtype,
                                    position_ids=position_ids)
        else:
            inv_freq = 1.0 / (10000.0 ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
            pos = (jnp.arange(q.shape[1], dtype=jnp.float32) if position_ids is None
                   else position_ids.astype(jnp.float32))
            freqs = jnp.einsum("...s,f->...sf", pos, inv_freq)
            emb = jnp.repeat(freqs, 2, axis=-1)  # f0,f0,f1,f1,...
            cos, sin = jnp.cos(emb).astype(q.dtype), jnp.sin(emb).astype(q.dtype)
    q, k = apply_rotary_pos_emb(q, k, cos, sin,
                                interleaved=not use_neox_rotary_style)
    return q, k, v


# ---------------------------------------------------------------------------
# attention (reference: flash_attn kernels + scaled_dot_product_attention)
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 scale=None):
    """paddle.nn.functional.scaled_dot_product_attention parity.

    Layout [batch, seq, num_heads, head_dim] (the reference's flash-attn
    layout).  Dispatches to the Pallas flash-attention kernel on TPU for
    the causal/no-mask cases; XLA fallback otherwise.
    """
    from ..ops import dispatch
    impl = dispatch.get("flash_attention")
    if impl is not None and attn_mask is None and dropout_p == 0.0:
        return impl(query, key, value, causal=is_causal, scale=scale)
    return _xla_attention(query, key, value, attn_mask, dropout_p, is_causal,
                          training, scale)


def _xla_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                   is_causal=False, training=True, scale=None):
    b, sq, h, d = query.shape
    sk = key.shape[1]
    kh = key.shape[2]
    if kh != h:  # grouped-query attention: repeat kv heads
        rep = h // kh
        key = jnp.repeat(key, rep, axis=2)
        value = jnp.repeat(value, rep, axis=2)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", query, key) * scale
    logits = logits.astype(jnp.float32)
    if is_causal:
        causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(causal[None, None], logits, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -jnp.inf)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(query.dtype)
    if dropout_p > 0.0 and training:
        probs = dropout(probs, p=dropout_p, training=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, value)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, training=True):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal, training=training)
    # The reference returns (out, softmax); softmax is only materialised when
    # return_softmax is set, which the flash path never supports.
    return out, None


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, label_smoothing=0.0):
    logp = jax.nn.log_softmax(input.astype(jnp.float32), axis=axis)
    if soft_label:
        if weight is not None:
            logp = logp * weight
        loss = -(label * logp).sum(axis=axis)
    else:
        num_classes = input.shape[axis]
        lab = label.squeeze(axis) if label.ndim == input.ndim else label
        nll = -jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32) % num_classes,
                                   axis=axis).squeeze(axis)
        if label_smoothing > 0.0:
            smooth = -logp.mean(axis=axis)
            nll = (1 - label_smoothing) * nll + label_smoothing * smooth
        valid = lab != ignore_index
        w = jnp.ones_like(nll)
        if weight is not None:
            w = jnp.take(jnp.asarray(weight, jnp.float32),
                         lab.astype(jnp.int32) % num_classes, axis=0)
        nll = jnp.where(valid, nll * w, 0.0)
        if reduction == "mean":
            # paddle weighted-mean semantics: divide by the sum of weights
            denom = jnp.where(valid, w, 0.0).sum()
            return nll.sum() / jnp.maximum(denom, 1e-12)
        loss = nll
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100, return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label, axis=axis,
                         ignore_index=ignore_index, reduction="none")
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean"):
    loss = jnp.square(input - label)
    return {"mean": loss.mean, "sum": loss.sum, "none": lambda: loss}[reduction]()


def l1_loss(input, label, reduction="mean"):
    loss = jnp.abs(input - label)
    return {"mean": loss.mean, "sum": loss.sum, "none": lambda: loss}[reduction]()


def binary_cross_entropy_with_logits(logit, label, reduction="mean", pos_weight=None):
    mx = jnp.clip(logit, 0, None)
    loss = mx - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if pos_weight is not None:
        loss = loss * (label * (pos_weight - 1) + 1)
    return {"mean": loss.mean, "sum": loss.sum, "none": lambda: loss}[reduction]()


def nll_loss(input, label, reduction="mean"):
    nll = -jnp.take_along_axis(input, label[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
    return {"mean": nll.mean, "sum": nll.sum, "none": lambda: nll}[reduction]()


# ---------------------------------------------------------------------------
# convolution / pooling / resize (SDXL ops breadth)
# ---------------------------------------------------------------------------

def _conv_pet(dtype):
    """Conv accumulation request: asking XLA for an f32 OUTPUT from bf16
    operands breaks the conv VJP (the rhs-transpose conv then pairs a
    bf16 operand with the f32 cotangent, which lax.conv rejects).  The
    TPU MXU accumulates bf16 convs in f32 internally regardless, so for
    low-precision operands we keep the operand dtype as the output."""
    return jnp.float32 if dtype == jnp.float32 else None


def _conv_dtypes(x, weight):
    """lax.conv demands matching operand dtypes; under AMP the reference
    white-lists conv to run in the LOW precision side (amp/auto_cast), so
    a mixed f32-activation/bf16-weight pair computes in bf16."""
    if x.dtype == weight.dtype:
        return x, weight
    narrow = min((x.dtype, weight.dtype),
                 key=lambda d: jnp.finfo(d).bits if
                 jnp.issubdtype(d, jnp.floating) else 99)
    return x.astype(narrow), weight.astype(narrow)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """Weight layout (out_c, in_c/groups, kh, kw), matching the reference."""
    x, weight = _conv_dtypes(x, weight)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = (padding, padding) if isinstance(padding, int) else tuple(padding)
        pad = [(p[0], p[0]), (p[1], p[1])]
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=_conv_pet(x.dtype)).astype(x.dtype)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(shape).astype(out.dtype)
    return out


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
    s = k if stride is None else ((stride, stride) if isinstance(stride, int) else tuple(stride))
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    if data_format == "NCHW":
        window = (1, 1, *k); strides = (1, 1, *s); pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, *k, 1); strides = (1, *s, 1); pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    count = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add, window, strides, pads)
    return summed / count


def max_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
    s = k if stride is None else ((stride, stride) if isinstance(stride, int) else tuple(stride))
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    if data_format == "NCHW":
        window = (1, 1, *k); strides = (1, 1, *s); pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, *k, 1); strides = (1, *s, 1); pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides, pads)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
    else:
        n, h, w, c = x.shape
    if size is None:
        sf = (scale_factor, scale_factor) if not isinstance(scale_factor, (tuple, list)) else scale_factor
        size = (int(h * sf[0]), int(w * sf[1]))
    if align_corners and mode == "bilinear" and size[0] > 1 and size[1] > 1:
        # endpoint-aligned sampling (out[i] at i*(in-1)/(out-1)) —
        # jax.image.resize only does half-pixel centers
        img = x if data_format == "NCHW" else jnp.moveaxis(x, -1, 1)
        yy = jnp.linspace(0.0, h - 1.0, size[0])
        xx = jnp.linspace(0.0, w - 1.0, size[1])
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy = (yy - y0)[:, None]
        wx = (xx - x0)[None, :]
        g = lambda yi, xi: img[:, :, yi[:, None], xi[None, :]]
        out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1) * (1 - wy) * wx
               + g(y1, x0) * wy * (1 - wx) + g(y1, x1) * wy * wx)
        if data_format != "NCHW":
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(x.dtype)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]
    if data_format == "NCHW":
        out = jax.image.resize(x, (n, c, size[0], size[1]), method=method)
    else:
        out = jax.image.resize(x, (n, size[0], size[1], c), method=method)
    return out.astype(x.dtype)


def pad(x, pad_width, mode="constant", value=0.0, data_format="NCHW"):
    """Paddle pad semantics: a flat [left, right, (top, bottom, (front,
    back))] list pads the spatial dims of NCL/NCHW/NCDHW (innermost dim
    first, the reference order); anything else passes through to jnp.pad."""
    if isinstance(pad_width, (list, tuple)) and \
            not isinstance(pad_width[0], (list, tuple)) and \
            len(pad_width) == 2 * (x.ndim - 2):
        pairs = [tuple(pad_width[i:i + 2])
                 for i in range(0, len(pad_width), 2)]  # innermost first
        spatial = list(reversed(pairs))
        if data_format.startswith("NC"):
            cfg = tuple([(0, 0), (0, 0)] + spatial)
        else:
            cfg = tuple([(0, 0)] + spatial + [(0, 0)])
    else:
        cfg = pad_width
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    return jnp.pad(x, cfg, mode={"reflect": "reflect", "replicate": "edge"}[mode])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    k = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else tuple(kernel_sizes)
    s = (strides, strides) if isinstance(strides, int) else tuple(strides)
    p = (paddings, paddings) if isinstance(paddings, int) else tuple(paddings)
    d = (dilations, dilations) if isinstance(dilations, int) else tuple(dilations)
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, k, s, [(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, patches.shape[1], -1)


# ---------------------------------------------------------------------------
# conv 1d/3d, transpose convs, adaptive pools, pixel shuffle
# (reference: python/paddle/nn/functional/conv.py, pooling.py, vision.py)
# ---------------------------------------------------------------------------

def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    """Weight layout (out_c, in_c/groups, k), matching the reference."""
    x, weight = _conv_dtypes(x, weight)
    stride = (stride,) if isinstance(stride, int) else tuple(stride)
    dilation = (dilation,) if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = padding if isinstance(padding, int) else padding[0]
        pad = [(p, p)]
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "OIH", "NHC"))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=_conv_pet(x.dtype)).astype(x.dtype)
    if bias is not None:
        shape = [1, -1, 1] if data_format == "NCL" else [1, 1, -1]
        out = out + bias.reshape(shape).astype(out.dtype)
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    x, weight = _conv_dtypes(x, weight)
    stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    dilation = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
        pad = [(pp, pp) for pp in p]
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW"
        else ("NDHWC", "OIDHW", "NDHWC"))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=_conv_pet(x.dtype)).astype(x.dtype)
    if bias is not None:
        shape = [1, -1, 1, 1, 1] if data_format == "NCDHW" else [1, 1, 1, 1, -1]
        out = out + bias.reshape(shape).astype(out.dtype)
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    """Gradient/fractionally-strided conv. Weight layout (in_c, out_c/groups,
    kh, kw) — the reference's Conv2DTranspose convention.

    Implemented as conv_general_dilated with lhs_dilation=stride (the
    standard XLA lowering of transpose conv; MXU-friendly, no scatter)."""
    x, weight = _conv_dtypes(x, weight)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, str):
        # 'SAME' (out = in*stride) / 'VALID' via lax.conv_transpose, which
        # handles transpose-conv string padding natively
        if groups != 1:
            raise NotImplementedError(
                "conv2d_transpose: string padding with groups>1 is not "
                "supported; pass explicit integer padding")
        d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
        # transpose_kernel=True swaps the kernel spec's I/O axes, so "OIHW"
        # here reads our (in, out, kh, kw) weight correctly
        dn = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" \
            else ("NHWC", "OIHW", "NHWC")
        out = jax.lax.conv_transpose(
            x, weight, strides=s, padding=padding.upper(), rhs_dilation=d,
            dimension_numbers=dn, transpose_kernel=True,
            preferred_element_type=_conv_pet(x.dtype)).astype(x.dtype)
        if bias is not None:
            shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
            out = out + bias.reshape(shape).astype(out.dtype)
        return out
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    op = (output_padding, output_padding) if isinstance(output_padding, int) \
        else tuple(output_padding)
    kh, kw = weight.shape[-2:]
    # effective kernel extent with dilation
    ekh, ekw = (kh - 1) * d[0] + 1, (kw - 1) * d[1] + 1
    pad = [(ekh - 1 - p[0], ekh - 1 - p[0] + op[0]),
           (ekw - 1 - p[1], ekw - 1 - p[1] + op[1])]
    # weight (I, O/g, kh, kw) → flip spatial, swap to (O, I/g, kh, kw)
    w = jnp.flip(weight, axis=(-2, -1))
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        i, og, *k = w.shape
        w = w.reshape(groups, i // groups, og, *k).swapaxes(1, 2) \
             .reshape(groups * og, i // groups, *k)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "OIHW", "NHWC"))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad, lhs_dilation=s,
        rhs_dilation=d, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=_conv_pet(x.dtype)).astype(x.dtype)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(shape).astype(out.dtype)
    return out


def _adaptive_pool2d(x, output_size, data_format, reduce_fn, pool2d_fn):
    """Shared adaptive-pool core: even windows fast-path through the regular
    pool; uneven windows use per-bucket slice+reduce in H then W (exact for
    max; exact for mean because every element in a bucket has equal weight
    within each pass)."""
    out = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    if data_format == "NCHW":
        n, c, h, w = x.shape
    else:
        n, h, w, c = x.shape
    if h % out[0] == 0 and w % out[1] == 0:
        kh, kw = h // out[0], w // out[1]
        return pool2d_fn(x, (kh, kw), stride=(kh, kw),
                         data_format=data_format)
    idx_h = [(int(i * h / out[0]), int(-(-((i + 1) * h) // out[0])))
             for i in range(out[0])]
    idx_w = [(int(j * w / out[1]), int(-(-((j + 1) * w) // out[1])))
             for j in range(out[1])]
    axis_h, axis_w = (2, 3) if data_format == "NCHW" else (1, 2)
    rows = [reduce_fn(jax.lax.slice_in_dim(x, a, b, axis=axis_h),
                      axis=axis_h, keepdims=True) for a, b in idx_h]
    xh = jnp.concatenate(rows, axis=axis_h)
    cols = [reduce_fn(jax.lax.slice_in_dim(xh, a, b, axis=axis_w),
                      axis=axis_w, keepdims=True) for a, b in idx_w]
    return jnp.concatenate(cols, axis=axis_w)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool2d(x, output_size, data_format, jnp.mean,
                            avg_pool2d)


def adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool2d(x, output_size, data_format, jnp.max,
                            max_pool2d)


def avg_pool1d(x, kernel_size, stride=None, padding=0):
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = k if stride is None else (stride if isinstance(stride, int) else stride[0])
    p = padding if isinstance(padding, int) else padding[0]
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 1, k), (1, 1, s),
                                   ((0, 0), (0, 0), (p, p)))
    count = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                  (1, 1, k), (1, 1, s), ((0, 0), (0, 0), (p, p)))
    return summed / count


def max_pool1d(x, kernel_size, stride=None, padding=0):
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = k if stride is None else (stride if isinstance(stride, int) else stride[0])
    p = padding if isinstance(padding, int) else padding[0]
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 1, k),
                                 (1, 1, s), ((0, 0), (0, 0), (p, p)))


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h * r, w * r, c // (r * r))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = x.transpose(0, 1, 3, 5, 2, 4)
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // r, w // r, c * r * r)


def instance_norm(x, weight=None, bias=None, eps=1e-5, data_format="NCHW"):
    """Per-(sample, channel) normalization over spatial dims."""
    spatial = tuple(range(2, x.ndim)) if data_format.startswith("NC") \
        else tuple(range(1, x.ndim - 1))
    mean = jnp.mean(x, axis=spatial, keepdims=True)
    var = jnp.var(x, axis=spatial, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    c_axis = 1 if data_format.startswith("NC") else -1
    if weight is not None:
        shape = [1] * x.ndim
        shape[c_axis] = -1
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1] * x.ndim
        shape[c_axis] = -1
        out = out + bias.reshape(shape)
    return out.astype(x.dtype)


def normalize(x, p=2, axis=1, epsilon=1e-12):
    norm = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, epsilon)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


def softsign(x):
    return jax.nn.soft_sign(x)


def tanhshrink(x):
    return x - jnp.tanh(x)


def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def prelu(x, weight):
    return jnp.where(x >= 0, x, weight * x)


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=False):
    if training:
        key = prandom.next_key()
        a = jax.random.uniform(key, x.shape, minval=lower, maxval=upper)
    else:
        a = (lower + upper) / 2
    return jnp.where(x >= 0, x, a * x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    """Channel-wise dropout (whole feature maps zeroed together)."""
    if not training or p == 0.0:
        return x
    key = prandom.next_key()
    shape = ((x.shape[0], x.shape[1], 1, 1) if data_format == "NCHW"
             else (x.shape[0], 1, 1, x.shape[3]))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    """Channel-wise dropout for 5-D inputs (whole volumes zeroed)."""
    if not training or p == 0.0:
        return x
    key = prandom.next_key()
    shape = ((x.shape[0], x.shape[1], 1, 1, 1) if data_format == "NCDHW"
             else (x.shape[0], 1, 1, 1, x.shape[4]))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


def alpha_dropout(x, p=0.5, training=True):
    """SELU-preserving dropout (reference: paddle.nn.functional
    alpha_dropout) — dropped units take the negative saturation value and
    the output is rescaled so self-normalization survives."""
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    neg_sat = -alpha * scale
    keep_p = 1.0 - p
    a = (keep_p + neg_sat ** 2 * keep_p * p) ** -0.5
    b = -a * neg_sat * p
    keep = jax.random.bernoulli(prandom.next_key(), keep_p, x.shape)
    return (a * jnp.where(keep, x, neg_sat) + b).astype(x.dtype)


def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    """Row-wise p-norm distance (reference: paddle.nn.PairwiseDistance)."""
    diff = jnp.abs(x - y) + epsilon
    if p == float("inf"):
        out = jnp.max(diff, axis=-1, keepdims=keepdim)
    else:
        out = jnp.sum(diff ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    return out


def local_response_norm(x, size=5, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    """AlexNet-era cross-channel normalization (reference:
    paddle.nn.functional.local_response_norm)."""
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    sq = x * x
    half = size // 2
    pad = [(0, 0)] * x.ndim
    pad[1] = (half, size - half - 1)
    acc = jnp.pad(sq, pad)
    # windowed channel sum via cumulative sum difference
    cs = jnp.cumsum(acc, axis=1)
    zeros = jnp.zeros_like(cs[:, :1])
    cs = jnp.concatenate([zeros, cs], axis=1)
    win = cs[:, size:] - cs[:, :-size]
    # reference formula (norm.py uses an avg_pool): alpha scales the MEAN
    # of the window, matching torch
    out = x / (k + alpha * win / size) ** beta
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


def channel_shuffle(x, groups, data_format="NCHW"):
    """Reference: paddle.nn.functional.channel_shuffle (ShuffleNet)."""
    if data_format == "NCHW":
        b, c, h, w = x.shape
        return x.reshape(b, groups, c // groups, h, w).swapaxes(1, 2) \
            .reshape(b, c, h, w)
    b, h, w, c = x.shape
    return x.reshape(b, h, w, groups, c // groups).swapaxes(3, 4) \
        .reshape(b, h, w, c)


def bilinear(x1, x2, weight, bias=None):
    """Reference: paddle.nn.functional.bilinear — out[b,o] =
    x1[b,:] @ W[o] @ x2[b,:] (+ bias)."""
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def zeropad2d(x, padding, data_format="NCHW"):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


def adaptive_max_pool1d(x, output_size):
    """NCL adaptive max pool."""
    b, c, l = x.shape
    o = output_size if isinstance(output_size, int) else output_size[0]
    starts = (jnp.arange(o) * l) // o
    ends = -(-(jnp.arange(1, o + 1) * l) // o)
    idx = jnp.arange(l)
    mask = (idx[None, :] >= starts[:, None]) & (idx[None, :] < ends[:, None])
    return jnp.max(jnp.where(mask[None, None], x[:, :, None, :], -jnp.inf),
                   axis=-1)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW"):
    """Reference: paddle.nn.functional.max_unpool2d — scatter pooled
    values back to their argmax positions (indices are flat per-map
    offsets, the reference/torch convention)."""
    if data_format != "NCHW":
        raise NotImplementedError("max_unpool2d supports NCHW")
    b, c, h, w = x.shape
    stride = stride or kernel_size
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    if output_size is None:
        oh = (h - 1) * st[0] - 2 * pd[0] + ks[0]
        ow = (w - 1) * st[1] - 2 * pd[1] + ks[1]
    else:
        oh, ow = output_size[-2:]
    flat = jnp.zeros((b, c, oh * ow), x.dtype)
    out = flat.at[jnp.arange(b)[:, None, None], jnp.arange(c)[None, :, None],
                  indices.reshape(b, c, -1)].set(x.reshape(b, c, -1))
    return out.reshape(b, c, oh, ow)


def kl_div(input, label, reduction="mean"):
    """input is log-probabilities (reference convention)."""
    out = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "mean":
        return out.mean()
    if reduction == "batchmean":
        return out.sum() / input.shape[0]
    if reduction == "sum":
        return out.sum()
    return out


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    diff = jnp.abs(input - label)
    out = jnp.where(diff < delta, 0.5 * diff * diff / delta,
                    diff - 0.5 * delta)
    if reduction == "mean":
        return out.mean()
    if reduction == "sum":
        return out.sum()
    return out


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    out = jnp.maximum(0.0, -label * (input - other) + margin)
    if reduction == "mean":
        return out.mean()
    if reduction == "sum":
        return out.sum()
    return out


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    eps = 1e-12
    out = -(label * jnp.log(jnp.clip(input, eps, None))
            + (1 - label) * jnp.log(jnp.clip(1 - input, eps, None)))
    if weight is not None:
        out = out * weight
    if reduction == "mean":
        return out.mean()
    if reduction == "sum":
        return out.sum()
    return out


# ---------------------------------------------------------------------------
# spatial sampling (reference: python/paddle/nn/functional/vision.py —
# affine_grid, grid_sample; common.py — fold, upsample)
# ---------------------------------------------------------------------------

def upsample(x, size=None, scale_factor=None, mode="nearest",
             data_format="NCHW"):
    """Alias of interpolate (reference keeps both)."""
    return interpolate(x, size=size, scale_factor=scale_factor, mode=mode,
                       data_format=data_format)


def affine_grid(theta, out_shape, align_corners=True):
    """theta: (N, 2, 3) affine matrices → sampling grid (N, H, W, 2) in
    normalized [-1, 1] coords (reference/torch semantics)."""
    n, h, w = out_shape[0], out_shape[-2], out_shape[-1]

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = axis_coords(h)
    xs = axis_coords(w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)   # (H, W, 3)
    theta = jnp.asarray(theta)                                # (N, 2, 3)
    return jnp.einsum("hwk,nik->nhwi", base, theta)           # (N, H, W, 2)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """Sample NCHW ``x`` at normalized grid locations (N, Hg, Wg, 2),
    xy-ordered like the reference/torch. bilinear|nearest;
    zeros|border|reflection padding."""
    n, c, h, w = x.shape

    def denorm(coord, size):
        coord = coord.astype(jnp.float32)
        if align_corners:
            return (coord + 1) * (size - 1) / 2
        return ((coord + 1) * size - 1) / 2

    gx = denorm(grid[..., 0], w)                              # (N, Hg, Wg)
    gy = denorm(grid[..., 1], h)

    def reflect(coord, size):
        if align_corners:
            span = 2 * (size - 1)
            if size == 1:
                return jnp.zeros_like(coord)
            coord = jnp.abs(coord) % span
            return jnp.where(coord > size - 1, span - coord, coord)
        span = 2 * size
        coord = jnp.abs(coord + 0.5) % span
        coord = jnp.where(coord > size - 0.5, span - coord, coord) - 0.5
        return jnp.clip(coord, 0, size - 1)

    def gather(ix, iy):
        """x[n, :, iy, ix] with out-of-range → 0 (zeros mode)."""
        inside = ((ix >= 0) & (ix <= w - 1) & (iy >= 0)
                  & (iy <= h - 1))
        ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
        batch = jnp.arange(n)[:, None, None]
        vals = x[batch, :, iyc, ixc]                          # (N,Hg,Wg,C)
        if padding_mode == "zeros":
            vals = jnp.where(inside[..., None], vals, 0.0)
        return vals

    if padding_mode == "reflection":
        gx, gy = reflect(gx, w), reflect(gy, h)
    elif padding_mode == "border":
        gx = jnp.clip(gx, 0, w - 1)
        gy = jnp.clip(gy, 0, h - 1)

    if mode == "nearest":
        out = gather(jnp.round(gx), jnp.round(gy))
        return jnp.moveaxis(out, -1, 1)

    x0, y0 = jnp.floor(gx), jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx = (gx - x0)[..., None]
    wy = (gy - y0)[..., None]
    out = (gather(x0, y0) * (1 - wx) * (1 - wy)
           + gather(x1, y0) * wx * (1 - wy)
           + gather(x0, y1) * (1 - wx) * wy
           + gather(x1, y1) * wx * wy)
    return jnp.moveaxis(out, -1, 1)                           # NCHW


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im — inverse of :func:`unfold`; overlaps are summed
    (reference: paddle.nn.functional.fold)."""
    oh, ow = ((output_sizes, output_sizes)
              if isinstance(output_sizes, int) else tuple(output_sizes))
    k = ((kernel_sizes, kernel_sizes)
         if isinstance(kernel_sizes, int) else tuple(kernel_sizes))
    s = (strides, strides) if isinstance(strides, int) else tuple(strides)
    p = (paddings, paddings) if isinstance(paddings, int) else tuple(paddings)
    d = (dilations, dilations) if isinstance(dilations, int) else tuple(dilations)
    n, ck, L = x.shape
    c = ck // (k[0] * k[1])
    nh = (oh + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
    nw = (ow + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
    cols = x.reshape(n, c, k[0], k[1], nh, nw)
    out = jnp.zeros((n, c, oh + 2 * p[0], ow + 2 * p[1]), x.dtype)
    for i in range(k[0]):          # static unroll: kernel sizes are small
        for j in range(k[1]):
            ys = i * d[0]
            xs = j * d[1]
            out = out.at[:, :, ys:ys + nh * s[0]:s[0],
                         xs:xs + nw * s[1]:s[1]].add(cols[:, :, i, j])
    return out[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]


# ---------------------------------------------------------------------------
# loss long tail (reference: python/paddle/nn/functional/loss.py)
# ---------------------------------------------------------------------------

def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference: paddle.nn.functional.ctc_loss over warpctc).

    log_probs: [T, B, C] log-probabilities (reference layout);
    labels: [B, L] padded label ids; lengths per sample. Lowers to
    optax's TPU-friendly lattice implementation.
    """
    import optax
    del norm_by_times
    # optax wants [B, T, C] logits and paddings
    logits = jnp.transpose(log_probs, (1, 0, 2))
    b, t, _ = logits.shape
    l = labels.shape[1]
    logit_pad = (jnp.arange(t)[None] >= input_lengths[:, None]).astype(
        jnp.float32)
    label_pad = (jnp.arange(l)[None] >= label_lengths[:, None]).astype(
        jnp.float32)
    per_seq = optax.ctc_loss(logits, logit_pad, labels, label_pad,
                             blank_id=blank)
    if reduction == "mean":
        # reference averages per-label-length then over batch
        return jnp.mean(per_seq / jnp.maximum(label_lengths, 1))
    if reduction == "sum":
        return jnp.sum(per_seq)
    return per_seq


def huber_loss(input, label, delta=1.0, reduction="mean"):
    d = input - label
    ad = jnp.abs(d)
    out = jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p),
                                 axis=-1), 1.0 / p)

    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    out = jnp.maximum(d_pos - d_neg + margin, 0.0)
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    cos = jnp.sum(input1 * input2, -1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    out = jnp.where(label > 0, 1.0 - cos, jnp.maximum(cos - margin, 0.0))
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    out = jnp.where(label > 0, input, jnp.maximum(margin - input, 0.0))
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


# round-3 tail (losses, lp/fractional pooling, gumbel softmax, rnnt) —
# see functional_tail3.py
from .functional_tail3 import *  # noqa: F401,F403,E402
from .functional_tail3 import (soft_margin_loss, multi_margin_loss,  # noqa: F401,E402
                               multi_label_soft_margin_loss,
                               triplet_margin_with_distance_loss,
                               poisson_nll_loss, gaussian_nll_loss,
                               sigmoid_focal_loss, dice_loss, npair_loss,
                               square_error_cost, rnnt_loss, gumbel_softmax,
                               lp_pool1d, lp_pool2d, max_unpool1d,
                               max_unpool3d, fractional_max_pool2d,
                               fractional_max_pool3d)


# round-4 tail (3-D pools, nd transpose convs, sequence/margin losses,
# sparse attention, gather_tree, hsigmoid) — see functional_tail4.py
from .functional_tail4 import *  # noqa: F401,F403,E402
from .functional_tail4 import (avg_pool3d, max_pool3d,  # noqa: F401,E402
                               adaptive_avg_pool1d, adaptive_max_pool1d,
                               adaptive_avg_pool3d, adaptive_max_pool3d,
                               conv1d_transpose, conv3d_transpose,
                               label_smooth, log_loss, sequence_mask,
                               temporal_shift, gather_tree, hsigmoid_loss,
                               margin_cross_entropy, class_center_sample,
                               sparse_attention, relu_, elu_, softmax_)


# static-graph interop: F.* also record onto static.Var placeholders
import sys as _sys  # noqa: E402

from ..static import enable_var_dispatch as _evd  # noqa: E402

_this = _sys.modules[__name__]
# only wrap callables that BELONG to this surface (defined in
# nn.functional* or re-exported from jax.nn) — dir() alone would also
# grab imported helpers like convert_dtype or typing.Optional
_evd(_this, [n for n in dir(_this)
             if getattr(getattr(_this, n, None), "__module__",
                        "").startswith(("paddle_tpu.nn", "jax"))])


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
