"""Recurrent layers: SimpleRNN / LSTM / GRU (+ single cells).

Reference: python/paddle/nn/layer/rnn.py (RNNBase, LSTM, GRU, *Cell classes;
cuDNN-backed on GPU).

TPU redesign: the time loop is ``jax.lax.scan`` — one compiled program, no
per-step dispatch; the (4h,h)·(h) gate matmuls batch into single MXU calls
per step. Multi-layer and bidirectional variants compose scans. Parameters
live on per-cell sublayers (cell_{k}[_reverse].weight_ih), but
state_dict()/set_state_dict() translate to/from the reference's flat naming
(weight_ih_l{k}[_reverse]) so reference state_dicts port."""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from . import initializer as I
from .layer import Layer


def _init_bound(hidden_size):
    return 1.0 / math.sqrt(hidden_size)


class RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, gates):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        b = _init_bound(hidden_size)
        g = gates * hidden_size
        self.weight_ih = self.create_parameter(
            (g, input_size), default_initializer=I.Uniform(-b, b))
        self.weight_hh = self.create_parameter(
            (g, hidden_size), default_initializer=I.Uniform(-b, b))
        self.bias_ih = self.create_parameter(
            (g,), is_bias=True, default_initializer=I.Uniform(-b, b))
        self.bias_hh = self.create_parameter(
            (g,), is_bias=True, default_initializer=I.Uniform(-b, b))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh"):
        super().__init__(input_size, hidden_size, 1)
        self.activation = jnp.tanh if activation == "tanh" else jax.nn.relu

    def forward(self, x, h):
        pre = (x @ self.weight_ih.T + self.bias_ih
               + h @ self.weight_hh.T + self.bias_hh)
        return self.activation(pre)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size):
        super().__init__(input_size, hidden_size, 4)

    def forward(self, x, state):
        h, c = state
        gates = (x @ self.weight_ih.T + self.bias_ih
                 + h @ self.weight_hh.T + self.bias_hh)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size):
        super().__init__(input_size, hidden_size, 3)

    def forward(self, x, h):
        gi = x @ self.weight_ih.T + self.bias_ih
        gh = h @ self.weight_hh.T + self.bias_hh
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n)
        return (1 - z) * n + z * h


class _RNNBase(Layer):
    """Stacked (and optionally bidirectional) scan over a cell type."""

    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", dropout=0.0, time_major=False):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.time_major = time_major
        self.dropout = dropout
        ndir = 2 if self.bidirectional else 1
        self._cells = []
        for layer in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer == 0 else hidden_size * ndir
                cell = self._make_cell(in_sz, hidden_size)
                suffix = "_reverse" if d else ""
                self.add_sublayer(f"cell_{layer}{suffix}", cell)
                self._cells.append((layer, d, cell))

    def _make_cell(self, in_sz, hidden):
        raise NotImplementedError

    def _zero_state(self, cell, batch):
        if self.MODE == "LSTM":
            z = jnp.zeros((batch, self.hidden_size))
            return (z, z)
        return jnp.zeros((batch, self.hidden_size))

    def _scan_one(self, cell, x_tbf, init, reverse=False, seq_len=None):
        """x_tbf: (T, B, F). Returns (T, B, H), final_state.

        With ``seq_len`` (B,), steps at t >= len keep the previous state and
        emit zeros, so padded positions never contaminate states/outputs.
        In the reverse direction the padded steps come FIRST in scan order
        and simply hold the initial state until the sequence's true tail."""
        params = dict(cell.named_parameters())
        T = x_tbf.shape[0]
        ts = jnp.arange(T)

        def step(state, inputs):
            from .layer import functional_call
            xt, t = inputs
            new = functional_call(cell, params, xt, state)
            if seq_len is not None:
                valid = (t < seq_len)[:, None]
                new = jax.tree.map(
                    lambda n, o: jnp.where(valid, n, o), new, state)
            h = new[0] if isinstance(new, tuple) else new
            if seq_len is not None:
                h = jnp.where((t < seq_len)[:, None], h, 0.0)
            return new, h

        final, ys = jax.lax.scan(step, init, (x_tbf, ts), reverse=reverse)
        return ys, final

    def forward(self, x, initial_states=None, sequence_length=None):
        # normalize to (T, B, F)
        if not self.time_major:
            x = jnp.swapaxes(x, 0, 1)
        T, B = x.shape[0], x.shape[1]
        ndir = 2 if self.bidirectional else 1
        finals = []
        inp = x
        for layer in range(self.num_layers):
            outs = []
            for d in range(ndir):
                cell = dict(
                    ((l, dd), c) for l, dd, c in self._cells)[(layer, d)]
                if initial_states is not None:
                    init = self._slice_state(initial_states,
                                             layer * ndir + d)
                else:
                    init = self._zero_state(cell, B)
                ys, fin = self._scan_one(cell, inp, init, reverse=bool(d),
                                         seq_len=sequence_length)
                outs.append(ys)
                finals.append(fin)
            inp = jnp.concatenate(outs, axis=-1) if ndir == 2 else outs[0]
            if self.dropout and layer < self.num_layers - 1 and self.training:
                # reference semantics: dropout between stacked layers only
                from . import functional as F
                inp = F.dropout(inp, self.dropout, training=True)
        out = inp if self.time_major else jnp.swapaxes(inp, 0, 1)
        return out, self._stack_finals(finals)

    # -- reference-convention state_dict translation -----------------------

    def _name_map(self):
        """cell_{k}{suffix}.{w} ↔ {w}_l{k}{suffix} (reference naming)."""
        m = {}
        ndir = 2 if self.bidirectional else 1
        for layer in range(self.num_layers):
            for d in range(ndir):
                suffix = "_reverse" if d else ""
                for w in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"):
                    m[f"cell_{layer}{suffix}.{w}"] = f"{w}_l{layer}{suffix}"
        return m

    def state_dict(self, *a, **k):
        sd = super().state_dict(*a, **k)
        m = self._name_map()
        return type(sd)((m.get(key, key), v) for key, v in sd.items())

    def set_state_dict(self, state_dict, *a, **k):
        inv = {v: key for key, v in self._name_map().items()}
        translated = {inv.get(key, key): v for key, v in state_dict.items()}
        return super().set_state_dict(translated, *a, **k)

    def _slice_state(self, states, idx):
        if self.MODE == "LSTM":
            h, c = states
            return (h[idx], c[idx])
        return states[idx]

    def _stack_finals(self, finals):
        if self.MODE == "LSTM":
            hs = jnp.stack([f[0] for f in finals])
            cs = jnp.stack([f[1] for f in finals])
            return (hs, cs)
        return jnp.stack(finals)


class SimpleRNN(_RNNBase):
    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", activation="tanh", dropout=0.0,
                 time_major=False):
        self._activation = activation
        super().__init__(input_size, hidden_size, num_layers, direction,
                         dropout, time_major)

    def _make_cell(self, in_sz, hidden):
        return SimpleRNNCell(in_sz, hidden, self._activation)


class LSTM(_RNNBase):
    MODE = "LSTM"

    def _make_cell(self, in_sz, hidden):
        return LSTMCell(in_sz, hidden)


class GRU(_RNNBase):
    MODE = "GRU"

    def _make_cell(self, in_sz, hidden):
        return GRUCell(in_sz, hidden)
