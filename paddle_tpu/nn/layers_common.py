"""Common layers (``paddle.nn.*`` parity).

Reference: python/paddle/nn/layer/{common,norm,conv,activation,transformer,
loss}.py.  Each layer stores parameters via ``create_parameter`` so they are
visible to the functional bridge and the pjit step-compiler.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..core import convert_dtype
from . import functional as F
from . import initializer as I
from .layer import Layer, ParamAttr


# ---------------------------------------------------------------------------
# containers
# ---------------------------------------------------------------------------

class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            layers = tuple(layers[0])
        for i, layer in enumerate(layers):
            if isinstance(layer, (list, tuple)):  # ("name", layer) pairs
                name, layer = layer
                self.add_sublayer(name, layer)
            else:
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx % len(self._sub_layers))]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for k, v in (sublayers or {}).items():
            self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def __len__(self):
        return len(self._sub_layers)


class Identity(Layer):
    def forward(self, x):
        return x


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------

class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None, partition=None, bias_partition=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if not isinstance(weight_attr, ParamAttr)
            else weight_attr.initializer, partition=partition)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_features,), attr=bias_attr, is_bias=True,
                partition=bias_partition)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """``paddle.nn.Embedding`` parity.

    ``sparse=True`` marks the weight for rows-sparse (SelectedRows)
    gradients: compute them with :meth:`rows_grad` and feed the result to
    ``Optimizer.apply`` (SGD scatter-add / Adam ``lazy_mode``) — the
    dense autodiff path is unaffected (XLA's scatter-add on the dense
    cotangent is already rows-shaped work on TPU)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None, partition=None):
        super().__init__()
        self.num_embeddings, self.embedding_dim = num_embeddings, embedding_dim
        self.padding_idx = padding_idx
        self.sparse = sparse
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0) if weight_attr is None else None,
            partition=partition)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def rows_grad(self, ids, grad_out):
        """SelectedRows gradient of ``forward(ids)`` w.r.t. the weight:
        (rows, values) for the optimizer's sparse rule."""
        from ..sparse.rows import embedding_rows_grad
        return embedding_rows_grad(ids, grad_out, self.num_embeddings,
                                   padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.mode = p, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, training=self.training, mode=self.mode)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ..ops import flatten
        return flatten(x, self.start_axis, self.stop_axis)


# ---------------------------------------------------------------------------
# normalization layers
# ---------------------------------------------------------------------------

class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            self.normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)


class RMSNorm(Layer):
    """Reference: paddle.incubate.nn.FusedRMSNorm / phi RmsNormKernel."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups, self.epsilon, self.data_format = num_groups, epsilon, data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class BatchNorm2D(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.momentum, self.epsilon, self.data_format = momentum, epsilon, data_format
        self.weight = self.create_parameter((num_features,), attr=weight_attr,
                                            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance", jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        if self.training:
            # Compiled-path note: running stats are NOT updated inside a jit
            # trace (pure function); use eager warmup or freeze BN for
            # training parity.  Normalisation itself uses batch stats.
            return F.batch_norm(x, self._mean, self._variance, self.weight,
                                self.bias, training=True, epsilon=self.epsilon,
                                data_format=self.data_format)
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=False, epsilon=self.epsilon,
                            data_format=self.data_format)


BatchNorm1D = BatchNorm2D  # normalisation over axis 1 in both cases here


# ---------------------------------------------------------------------------
# activations as layers
# ---------------------------------------------------------------------------

def _act_layer(fn, name):
    class _Act(Layer):
        def forward(self, x):
            return fn(x)
    _Act.__name__ = name
    return _Act


ReLU = _act_layer(F.relu, "ReLU")
GELU = _act_layer(F.gelu, "GELU")
Silu = _act_layer(F.silu, "Silu")
Sigmoid = _act_layer(F.sigmoid, "Sigmoid")
Tanh = _act_layer(F.tanh, "Tanh")
Softmax = _act_layer(F.softmax, "Softmax")
LeakyReLU = _act_layer(F.leaky_relu, "LeakyReLU")
Hardswish = _act_layer(F.hardswish, "Hardswish")
Hardsigmoid = _act_layer(F.hardsigmoid, "Hardsigmoid")
Softplus = _act_layer(F.softplus, "Softplus")
Mish = _act_layer(F.mish, "Mish")


# ---------------------------------------------------------------------------
# conv / pooling
# ---------------------------------------------------------------------------

class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k[0] * k[1] // groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *k), attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = None
        if bias_attr is not False:
            bound = 1 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor, self.mode = size, scale_factor, mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.data_format)


# ---------------------------------------------------------------------------
# attention / transformer
# ---------------------------------------------------------------------------

class MultiHeadAttention(Layer):
    """``paddle.nn.MultiHeadAttention`` parity (self/cross attention).

    Reference: python/paddle/nn/layer/transformer.py.  Internally uses the
    flash-attention dispatch, so on TPU this lowers to the Pallas kernel.
    """

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim, vdim = kdim or embed_dim, vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        b, sq = query.shape[:2]
        q = self.q_proj(query).reshape(b, sq, self.num_heads, self.head_dim)
        k = self.k_proj(key).reshape(b, key.shape[1], self.num_heads, self.head_dim)
        v = self.v_proj(value).reshape(b, value.shape[1], self.num_heads, self.head_dim)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        out = out.reshape(b, sq, self.embed_dim)
        return self.out_proj(out)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = dict(d_model=d_model, nhead=nhead,
                            dim_feedforward=dim_feedforward, dropout=dropout,
                            activation=activation, attn_dropout=attn_dropout,
                            act_dropout=act_dropout,
                            normalize_before=normalize_before)
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead,
                                            dropout=attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = {"relu": F.relu, "gelu": F.gelu}[activation]

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = residual + self.dropout1(self.self_attn(src, attn_mask=src_mask))
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout_act(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


def _replicate_prototype(proto, num_layers):
    """paddle semantics: the prototype IS layer 0; later layers are fresh
    instances (so each gets independent random init, and weights loaded into
    the prototype survive as layer 0). Fresh construction only when the
    prototype is exactly a stock layer class whose ctor args were captured
    in _config; subclasses (unknown signatures) fall back to deepcopy."""
    import copy
    if not isinstance(proto, Layer):        # factory callable
        return [proto() for _ in range(num_layers)]
    exact = type(proto) in (TransformerEncoderLayer, TransformerDecoderLayer)
    if exact and hasattr(proto, "_config"):
        make = lambda: type(proto)(**proto._config)
    else:
        make = lambda: copy.deepcopy(proto)
    return [proto] + [make() for _ in range(num_layers - 1)]


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(_replicate_prototype(encoder_layer,
                                                     num_layers))
        self.norm = norm

    def forward(self, src, src_mask=None):
        for layer in self.layers:
            src = layer(src, src_mask=src_mask)
        if self.norm is not None:
            src = self.norm(src)
        return src


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, label_smoothing=0.0):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction
        self.soft_label, self.axis, self.label_smoothing = soft_label, axis, label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction, soft_label=self.soft_label,
                               axis=self.axis, label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction="mean", pos_weight=None):
        super().__init__()
        self.reduction, self.pos_weight = reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.reduction,
                                                  self.pos_weight)


class NLLLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.reduction)


class TransformerDecoderLayer(Layer):
    """Reference: python/paddle/nn/layer/transformer.py TransformerDecoderLayer
    (self-attn + cross-attn + FFN, pre/post-norm)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = dict(d_model=d_model, nhead=nhead,
                            dim_feedforward=dim_feedforward, dropout=dropout,
                            activation=activation, attn_dropout=attn_dropout,
                            act_dropout=act_dropout,
                            normalize_before=normalize_before)
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = {"relu": F.relu, "gelu": F.gelu}[activation]

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = residual + self.dropout1(self.self_attn(tgt, attn_mask=tgt_mask))
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = residual + self.dropout2(
            self.cross_attn(tgt, memory, memory, attn_mask=memory_mask))
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout_act(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(_replicate_prototype(decoder_layer,
                                                     num_layers))
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        for layer in self.layers:
            tgt = layer(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
        if self.norm is not None:
            tgt = self.norm(tgt)
        return tgt


class Transformer(Layer):
    """Full encoder-decoder (reference: paddle.nn.Transformer)."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False):
        super().__init__()
        enc_layer = TransformerEncoderLayer(
            d_model, nhead, dim_feedforward, dropout, activation,
            attn_dropout, act_dropout, normalize_before)
        dec_layer = TransformerDecoderLayer(
            d_model, nhead, dim_feedforward, dropout, activation,
            attn_dropout, act_dropout, normalize_before)
        enc_norm = LayerNorm(d_model) if normalize_before else None
        dec_norm = LayerNorm(d_model) if normalize_before else None
        self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model, self.nhead = d_model, nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp
        return jnp.where(
            jnp.tril(jnp.ones((length, length), jnp.bool_)), 0.0, -jnp.inf)
