"""``paddle.nn.quant`` parity: weight-only quantization for serving.

Reference: python/paddle/nn/quant/quantized_linear.py (weight_quantize /
weight_dequantize / weight_only_linear / llm_int8_linear over the Cutlass
fpA_intB GEMM — SURVEY §2.1 Cutlass row).  Decode is HBM-bandwidth-bound
(docs/BENCH.md "Decode throughput"): at batch 1 the parameter stream IS
the roofline, so storing weights as int8 (or packed int4) halves
(quarters) the bytes the MXU waits on.

TPU-first design: no custom GEMM — the weight is stored quantized in HBM
and dequantized *inside* the XLA matmul fusion (convert+scale fuse into
the dot's operand read; Mosaic emits the widening on the fly), which is
exactly what the reference's fpA_intB kernel hand-writes.  Scales are
per-out-channel (or per-(group, out-channel) for ``group_size``>0), so
for the ungrouped path the scale commutes out of the contraction and is
applied AFTER the int8 matmul — the hot loop reads only int8.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .layer import Layer

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "QuantizedLinear", "quantize_linears"]

_QMAX = {"weight_only_int8": 127.0, "weight_only_int4": 7.0,
         "llm.int8": 127.0}


def _check_algo(algo: str) -> None:
    if algo not in _QMAX:
        raise ValueError(f"unsupported algo {algo!r}; one of {list(_QMAX)}")


def _use_int4_kernel() -> bool:
    """The fused int4 kernel is a TPU Mosaic kernel; CPU tests keep the
    XLA reference formulation (numerically identical — the kernel's own
    tests assert exactness in interpret mode)."""
    import os

    if os.environ.get("PDTPU_INT4_KERNEL", "1") == "0":
        return False
    return jax.default_backend() == "tpu"


def _use_int8_kernel() -> bool:
    """Same gate for the fused int8 kernel (ops/pallas/int8_matmul.py);
    PDTPU_INT8_KERNEL=0 pins the XLA formulation for A/B runs."""
    import os

    if os.environ.get("PDTPU_INT8_KERNEL", "1") == "0":
        return False
    return jax.default_backend() == "tpu"


def _active_mesh():
    """The physical mesh entered via ``with mesh:`` (None outside).
    Mosaic kernels cannot be auto-partitioned by GSPMD: under a mesh the
    kernel needs an explicit shard_map (column-parallel path below) or
    the XLA fallback.  One definition lives in ops/pallas."""
    try:
        from ..ops.pallas import _active_mesh as impl
    except ImportError:  # pragma: no cover — jax internals moved
        return None
    return impl()


def _kernel_eligible(weight_scale, n_tokens) -> bool:
    """One definition of when the fused int4 kernel serves: per-channel
    scales and decode/serving token counts (prefill's big-M matmuls
    amortise the weight stream in XLA and would blow the kernel's VMEM
    x-tiles)."""
    return (weight_scale.ndim == 1 and n_tokens <= 256
            and _use_int4_kernel())


def _int8_kernel_eligible(weight_scale, n_tokens) -> bool:
    """Same shape gate for the fused int8 kernel: decode-sized token
    counts where the weight stream is the roofline."""
    return (weight_scale.ndim == 1 and n_tokens <= 256
            and _use_int8_kernel())


def _int8_matmul_fn():
    from ..ops.pallas.int8_matmul import int8_matmul
    return int8_matmul


def _n_tokens(x) -> int:
    n = 1
    for d in x.shape[:-1]:
        n *= d
    return n


def _kernel_column_sharded(matmul_fn, x2d, weight, scale, mesh):
    """shard_map'd quantized matmul kernel for the COLUMN-parallel
    layout: weight (K|K2, N) split over mp on N, per-channel scales
    split with it — each shard runs the kernel on its own columns and no
    cross-device reduction is needed (that is what makes column the safe
    case; row-parallel contracts over a sharded K and keeps the XLA
    path, whose psum GSPMD inserts).  The token dim rides the data axes
    when it divides them, so a dp-sharded serving batch is not gathered.
    Shared by the int4 and int8 kernels."""
    from ..core.compat import shard_map
    from jax.sharding import PartitionSpec as P

    data_axes = tuple(a for a in ("dp", "sharding")
                      if a in mesh.axis_names and mesh.shape[a] > 1)
    dsize = 1
    for a in data_axes:
        dsize *= mesh.shape[a]
    bt = data_axes if (data_axes and x2d.shape[0] % dsize == 0) else None

    f = shard_map(
        lambda a, w, s: matmul_fn(a, w, s),
        mesh=mesh,
        in_specs=(P(bt, None), P(None, "mp"), P("mp")),
        out_specs=P(bt, "mp"),
        check_vma=False)
    return f(x2d, weight, scale)


def _int4_kernel_column_sharded(x2d, weight, scale, mesh):
    return _kernel_column_sharded(_int4_matmul_fn(), x2d, weight, scale,
                                  mesh)


def _int4_matmul_fn():
    from ..ops.pallas.int4_matmul import int4_matmul
    return int4_matmul


def _pack_int4(q):
    """(in, out) int4-valued int8 -> (in//2, out) int8, two nibbles per
    byte: row 2i in the low nibble, row 2i+1 in the high nibble.  Packing
    along the CONTRACTION axis keeps out-channel scales per-column."""
    if q.shape[0] % 2:
        raise ValueError("int4 packing needs an even in_features "
                         f"(got {q.shape[0]})")
    lo = q[0::2] & 0x0F
    hi = jnp.left_shift(q[1::2], 4)
    return (lo | hi).astype(jnp.int8)


def _unpack_int4(packed):
    """Inverse of :func:`_pack_int4` — arithmetic shifts restore the sign
    of each nibble."""
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    hi = jnp.right_shift(packed, 4)
    n2, out = packed.shape
    return jnp.stack([lo, hi], axis=1).reshape(2 * n2, out)


def weight_quantize(x, algo: str = "weight_only_int8", group_size: int = -1):
    """Quantize a (in_features, out_features) weight for weight-only
    serving.  Returns ``(quantized weight, scale)``:

    - int8: weight (in, out) int8, scale (out,) f32
    - int4: weight (in//2, out) int8 (packed nibbles), scale (out,) f32
    - group_size > 0: scale (in//group_size, out) f32 (per-group absmax,
      the reference's groupwise int4 mode)
    """
    _check_algo(algo)
    xf = jnp.asarray(x).astype(jnp.float32)
    if xf.ndim != 2:
        raise ValueError(f"weight must be 2-D (in, out); got {xf.shape}")
    qmax = _QMAX[algo]
    if group_size and group_size > 0:
        n_in, n_out = xf.shape
        if n_in % group_size:
            raise ValueError(f"in_features {n_in} not divisible by "
                             f"group_size {group_size}")
        g = xf.reshape(n_in // group_size, group_size, n_out)
        scale = jnp.max(jnp.abs(g), axis=1) / qmax + 1e-12
        q = jnp.round(g / scale[:, None, :]).reshape(n_in, n_out)
    else:
        scale = jnp.max(jnp.abs(xf), axis=0) / qmax + 1e-12
        q = jnp.round(xf / scale)
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8)
    if algo == "weight_only_int4":
        q = _pack_int4(q)
    return q, scale


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      group_size: int = -1, out_dtype=jnp.float32):
    """Reconstruct the float weight (the reference's weight_dequantize)."""
    _check_algo(algo)
    q = _unpack_int4(x) if algo == "weight_only_int4" else jnp.asarray(x)
    qf = q.astype(out_dtype)
    if scale.ndim == 2:  # groupwise
        n_in, n_out = qf.shape
        gs = group_size if group_size and group_size > 0 \
            else n_in // scale.shape[0]
        return (qf.reshape(-1, gs, n_out)
                * scale[:, None, :].astype(out_dtype)).reshape(n_in, n_out)
    return qf * scale.astype(out_dtype)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", group_size: int = -1):
    """y = x @ dequant(weight) + bias, with the weight stored int8/int4.

    Reference: paddle.nn.quant.weight_only_linear (fpA_intB Cutlass GEMM).
    Per-out-channel scales commute out of the contraction: the matmul
    reads raw int8 (widened in-register by Mosaic) and the scale is one
    fused multiply on the (tiny) output tile.  Groupwise scales can't
    commute, so that path dequantizes into the matmul fusion instead."""
    algo = ("weight_only_int4" if weight_dtype in ("int4", "weight_only_int4")
            else "weight_only_int8")
    x = jnp.asarray(x)
    if weight_scale is None:
        raise ValueError("weight_scale is required (from weight_quantize)")
    if (algo == "weight_only_int4" and _kernel_eligible(weight_scale, _n_tokens(x))
            and _active_mesh() is None):
        # Under an ACTIVE MESH this generic entry falls back to XLA (GSPMD
        # cannot auto-partition Mosaic kernels, and this entry cannot know
        # the caller's weight sharding); the column-parallel layer routes
        # through the explicit shard_map instead.
        # fused dequant-in-matmul Pallas kernel: nibbles unpacked in VMEM,
        # HBM streams the PACKED bytes.  The XLA formulation below
        # materialises the unpacked weight to HBM every call — measured
        # ~8x slower at 7B-shaped GEMVs (docs/BENCH.md round 5)
        lead = x.shape[:-1]
        y = _int4_matmul_fn()(x.reshape(-1, x.shape[-1]),
                              jnp.asarray(weight), weight_scale)
        y = y.reshape(*lead, y.shape[-1])
        return y if bias is None else y + bias
    if (algo == "weight_only_int8"
            and _int8_kernel_eligible(weight_scale, _n_tokens(x))
            and _active_mesh() is None):
        # fused int8 dequant-in-matmul (ops/pallas/int8_matmul.py): HBM
        # streams the raw int8 bytes, the widening + per-channel scale
        # run in VMEM — serving's decode GEMVs stop dequantizing in fp.
        # Same mesh caveat as int4: the column-parallel layer routes
        # multi-chip through the explicit shard_map instead.
        lead = x.shape[:-1]
        y = _int8_matmul_fn()(x.reshape(-1, x.shape[-1]),
                              jnp.asarray(weight), weight_scale)
        y = y.reshape(*lead, y.shape[-1])
        return y if bias is None else y + bias
    if weight_scale.ndim == 2:  # groupwise: dequant fuses into the dot
        w = weight_dequantize(weight, weight_scale, algo=algo,
                              group_size=group_size, out_dtype=x.dtype)
        y = x @ w
    else:
        q = _unpack_int4(weight) if algo == "weight_only_int4" \
            else jnp.asarray(weight)
        acc = jax.lax.dot_general(
            x, q.astype(x.dtype), (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        y = (acc * weight_scale).astype(x.dtype)
    if bias is not None:
        y = y + bias
    return y


def llm_int8_linear(x, weight, weight_scale=None, threshold: float = 6.0):
    """LLM.int8() decomposition (reference:
    paddle.nn.quant.llm_int8_linear): activation features whose absmax
    exceeds ``threshold`` go through a float matmul against the
    dequantized weight rows; the rest go int8 x int8 into the MXU's
    int32 accumulator with dynamic per-token activation scales."""
    if weight_scale is None:
        raise ValueError("weight_scale is required (from weight_quantize)")
    x = jnp.asarray(x)
    q = jnp.asarray(weight)
    feat_max = jnp.max(jnp.abs(x.astype(jnp.float32)),
                       axis=tuple(range(x.ndim - 1)))
    outlier = feat_max > threshold                       # (in,)
    # int8 branch: zero outlier features out of the quantized path
    x_in = jnp.where(outlier, 0.0, x.astype(jnp.float32))
    x_scale = jnp.max(jnp.abs(x_in), axis=-1, keepdims=True) / 127.0 + 1e-12
    x_q = jnp.round(x_in / x_scale).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y_int8 = acc.astype(jnp.float32) * x_scale * weight_scale
    # outlier branch: float matmul on the few loud features
    w_out = q.astype(jnp.float32) * weight_scale
    x_out = jnp.where(outlier, x.astype(jnp.float32), 0.0)
    y = (y_int8 + x_out @ w_out).astype(x.dtype)
    return y


class QuantizedLinear(Layer):
    """Weight-only replacement for ``nn.Layer`` Linears at serving time —
    created by :func:`quantize_linears`.  A real ``nn.Layer`` (so
    ``.eval()``/``state_dict()``/sublayer walks keep working) whose
    weight lives in int8/packed-int4 BUFFERS, not trainable parameters —
    weight-only quantization is a serving transform, not QAT."""

    def __init__(self, linear, algo: str = "weight_only_int8",
                 group_size: int = -1):
        super().__init__()
        self.in_features = linear.in_features
        self.out_features = linear.out_features
        self.algo = algo
        self.group_size = group_size
        qw, scale = weight_quantize(jnp.asarray(linear.weight), algo=algo,
                                    group_size=group_size)
        self.register_buffer("weight", qw)
        self.register_buffer("weight_scale", scale)
        self.register_buffer(
            "bias", None if linear.bias is None else jnp.asarray(linear.bias))
        self._wdtype = "int4" if algo == "weight_only_int4" else "int8"

    def forward(self, x):
        return weight_only_linear(x, self.weight, bias=self.bias,
                                  weight_scale=self.weight_scale,
                                  weight_dtype=self._wdtype,
                                  group_size=self.group_size)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, algo={self.algo}")


class QuantizedColumnParallelLinear(Layer):
    """Weight-only variant of distributed.ColumnParallelLinear — same
    activation sharding constraints, int8/int4 weight stream."""

    def __init__(self, host, algo="weight_only_int8", group_size=-1):
        super().__init__()
        self.gather_output = host.gather_output
        self.sequence_parallel = host.sequence_parallel
        self.out_features = host.out_features
        self.algo, self.group_size = algo, group_size
        qw, s = weight_quantize(jnp.asarray(host.weight), algo=algo,
                                group_size=group_size)
        self.register_buffer("weight", qw)
        self.register_buffer("weight_scale", s)
        self.register_buffer(
            "bias", None if host.bias is None else jnp.asarray(host.bias))
        self._wdtype = "int4" if algo == "weight_only_int4" else "int8"

    def forward(self, x):
        from ..distributed.mp_layers import act_constrain
        if self.sequence_parallel:
            x = act_constrain(x, "mp", None)
        mesh = _active_mesh()
        sharded_fn = None
        if mesh is not None and "mp" in mesh.axis_names:
            if self._wdtype == "int4" and _kernel_eligible(
                    self.weight_scale, _n_tokens(x)):
                sharded_fn = _int4_matmul_fn()
            elif self._wdtype == "int8" and _int8_kernel_eligible(
                    self.weight_scale, _n_tokens(x)):
                sharded_fn = _int8_matmul_fn()
        if sharded_fn is not None:
            # multi-chip serving: explicit shard_map over mp (column split
            # needs no reduction) — GSPMD cannot partition the kernel
            y = _kernel_column_sharded(
                sharded_fn, x.reshape(-1, x.shape[-1]), self.weight,
                self.weight_scale, mesh)
            y = y.reshape(*x.shape[:-1], y.shape[-1])
            if self.bias is not None:
                y = y + self.bias
        else:
            y = weight_only_linear(x, self.weight, bias=self.bias,
                                   weight_scale=self.weight_scale,
                                   weight_dtype=self._wdtype,
                                   group_size=self.group_size)
        return act_constrain(y, None,
                             None if self.gather_output else "mp")


class QuantizedRowParallelLinear(Layer):
    """Weight-only variant of distributed.RowParallelLinear."""

    def __init__(self, host, algo="weight_only_int8", group_size=-1):
        super().__init__()
        self.input_is_parallel = host.input_is_parallel
        self.sequence_parallel = host.sequence_parallel
        self.algo, self.group_size = algo, group_size
        qw, s = weight_quantize(jnp.asarray(host.weight), algo=algo,
                                group_size=group_size)
        self.register_buffer("weight", qw)
        self.register_buffer("weight_scale", s)
        self.register_buffer(
            "bias", None if host.bias is None else jnp.asarray(host.bias))
        self._wdtype = "int4" if algo == "weight_only_int4" else "int8"

    def forward(self, x):
        from ..distributed.mp_layers import act_constrain
        if self.input_is_parallel:
            x = act_constrain(x, None, "mp")
        y = weight_only_linear(x, self.weight, bias=None,
                               weight_scale=self.weight_scale,
                               weight_dtype=self._wdtype,
                               group_size=self.group_size)
        if self.sequence_parallel:
            y = act_constrain(y, "mp", None)
        else:
            y = act_constrain(y, None, None)
        if self.bias is not None:
            y = y + self.bias
        return y


def quantize_linears(model, algo: str = "weight_only_int8",
                     group_size: int = -1,
                     predicate: Optional[callable] = None) -> int:
    """Swap every Linear-like layer under ``model`` — ``nn.Linear``,
    ``distributed.ColumnParallelLinear``, ``distributed.RowParallelLinear``
    — for its weight-only quantized variant (in place), returning the
    swap count.  This is the serving-side entry point: run it on a model
    before ``generate()``/Predictor decode and every projection streams
    int8 — stacked with the int8 KV cache it attacks both halves of
    decode's HBM bytes.  ``predicate(name, layer) -> bool`` filters
    (e.g. skip ``lm_head`` for quality)."""
    from ..distributed.mp_layers import (ColumnParallelLinear,
                                         RowParallelLinear)
    from .layers_common import Linear

    swaps = {Linear: QuantizedLinear,
             ColumnParallelLinear: QuantizedColumnParallelLinear,
             RowParallelLinear: QuantizedRowParallelLinear}
    count = 0
    seen = set()
    stack = [model]
    while stack:
        layer = stack.pop()
        if id(layer) in seen:
            continue
        seen.add(id(layer))
        subs = getattr(layer, "_sub_layers", None)
        if not subs:
            continue
        for name, sub in list(subs.items()):
            cls = swaps.get(type(sub))
            if cls is not None and (predicate is None
                                    or predicate(name, sub)):
                # setattr, not subs[name]=: Layer.__setattr__ mirrors
                # sublayers into __dict__, and attribute access reads
                # __dict__ first — a dict-only swap leaves the float
                # layer live at every self.proj(x) call site
                setattr(layer, name, cls(sub, algo=algo,
                                         group_size=group_size))
                count += 1
            else:
                stack.append(sub)
    return count
