"""``paddle.nn.utils`` parity: weight_norm, spectral_norm,
parameters_to_vector / vector_to_parameters.

Reference: python/paddle/nn/utils/ (weight_norm_hook.py,
spectral_norm_hook.py, transform_parameters.py).

TPU redesign: the reference reparameterizes with forward pre-hooks that
mutate ``layer.weight`` in place. Under functional jax the same effect is
a wrapper Layer that owns the reparameterized leaves (``weight_g``/
``weight_v``; spectral ``u``) and computes the effective weight inside
the traced forward — so the reparameterization differentiates and jits
like any other computation.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from .layer import Layer

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w.astype(jnp.float32)), axis=axes,
                            keepdims=True))


class WeightNormWrapper(Layer):
    """w = g * v / ||v||  (per-slice along ``dim``)."""

    def __init__(self, layer: Layer, name: str = "weight", dim: int = 0):
        super().__init__()
        self.layer = layer
        self.pname = name
        self.dim = dim
        w = getattr(layer, name)
        g = _norm_except(w, dim).astype(w.dtype)
        self.weight_g = self.create_parameter(g.shape)
        self.weight_v = self.create_parameter(w.shape)
        self.weight_g = g
        self.weight_v = w
        # the inner weight is no longer a trainable parameter (reference:
        # weight_norm deletes it and re-adds weight_g/weight_v)
        layer._parameters.pop(name, None)
        layer._param_meta.pop(name, None)

    def forward(self, *args, **kwargs):
        v = self.weight_v
        w = (self.weight_g.astype(jnp.float32)
             * v.astype(jnp.float32) / _norm_except(v, self.dim)).astype(
                 v.dtype)
        # swap the effective weight in functionally for this call
        old = getattr(self.layer, self.pname)
        setattr(self.layer, self.pname, w)
        try:
            return self.layer(*args, **kwargs)
        finally:
            setattr(self.layer, self.pname, old)


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0):
    return WeightNormWrapper(layer, name, dim)


def remove_weight_norm(wrapped: "WeightNormWrapper") -> Layer:
    """Bake the current effective weight back into the inner layer."""
    v = wrapped.weight_v
    w = (wrapped.weight_g.astype(jnp.float32) * v.astype(jnp.float32)
         / _norm_except(v, wrapped.dim)).astype(v.dtype)
    setattr(wrapped.layer, wrapped.pname, w)
    return wrapped.layer


class SpectralNormWrapper(Layer):
    """w / sigma_max(w), sigma estimated by power iteration whose state
    (u) rides as a buffer (reference: spectral_norm_hook)."""

    def __init__(self, layer: Layer, name: str = "weight",
                 n_power_iterations: int = 1, eps: float = 1e-12, dim: int = 0):
        super().__init__()
        self.layer = layer
        self.pname = name
        self.n_iters = n_power_iterations
        self.eps = eps
        self.dim = dim
        w = getattr(layer, name)
        h = w.shape[dim]
        self.register_buffer("u", jax.random.normal(
            jax.random.key(0), (h,), jnp.float32))

    def forward(self, *args, **kwargs):
        w = getattr(self.layer, self.pname)
        mat = jnp.moveaxis(w, self.dim, 0).reshape(w.shape[self.dim], -1)
        mat = mat.astype(jnp.float32)
        u = self.u
        # v is defined even for n_power_iterations=0 (reference accepts 0
        # and reuses the cached u); for n>=1 the iteration order is
        # unchanged: v = norm(matT u); u = norm(mat v), repeated
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + self.eps)
        for it in range(self.n_iters):
            if it:
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        sigma = u @ mat @ v
        if not isinstance(u, jax.core.Tracer):
            # persist power-iteration state only in eager mode — storing a
            # tracer would leak it across jit traces (under jit each call
            # re-iterates from the last eager state, which is stable)
            self.u = jax.lax.stop_gradient(u)
        w_sn = (w.astype(jnp.float32) / sigma).astype(w.dtype)
        old = getattr(self.layer, self.pname)
        setattr(self.layer, self.pname, w_sn)
        try:
            return self.layer(*args, **kwargs)
        finally:
            setattr(self.layer, self.pname, old)


def spectral_norm(layer: Layer, name: str = "weight",
                  n_power_iterations: int = 1, eps: float = 1e-12,
                  dim: int = 0):
    return SpectralNormWrapper(layer, name, n_power_iterations, eps, dim)


def parameters_to_vector(parameters: Sequence[jax.Array]) -> jax.Array:
    return jnp.concatenate([jnp.ravel(p) for p in parameters])


def vector_to_parameters(vec: jax.Array,
                         parameters: Sequence[jax.Array]) -> List[jax.Array]:
    out = []
    offset = 0
    for p in parameters:
        n = int(p.size)
        out.append(vec[offset:offset + n].reshape(p.shape).astype(p.dtype))
        offset += n
    return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Reference: paddle.nn.utils.clip_grad_norm_ — functional variant:
    jax arrays are immutable, so this takes GRADIENTS and returns the
    clipped gradients plus the total norm (rebind at the call site)."""
    import jax.numpy as jnp
    grads = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    if norm_type == float("inf"):
        total = jnp.max(jnp.asarray([jnp.abs(g).max() for g in grads]))
    else:
        total = jnp.sum(jnp.asarray(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) ** (
                1.0 / norm_type)
    if error_if_nonfinite:
        import jax as _jax
        if not isinstance(total, _jax.core.Tracer) and \
                not bool(jnp.isfinite(total)):
            raise RuntimeError(
                f"gradient norm is {float(total)}; set "
                "error_if_nonfinite=False to clip anyway")
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    clipped = [g * scale for g in grads]
    out = clipped if isinstance(parameters, (list, tuple)) else clipped[0]
    return out, total


def clip_grad_value_(parameters, clip_value):
    """Reference: paddle.nn.utils.clip_grad_value_ — functional variant
    (returns clipped gradients; see clip_grad_norm_)."""
    import jax.numpy as jnp
    grads = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    clipped = [jnp.clip(g, -clip_value, clip_value) for g in grads]
    return clipped if isinstance(parameters, (list, tuple)) else clipped[0]
