"""The Layer (module) system.

Paddle-parity surface of ``paddle.nn.Layer`` (reference:
python/paddle/nn/layer/layers.py) with a TPU-first execution model: a Layer
is a *container of named parameters* plus a forward function; the parameters
can be extracted as a flat pytree and the forward run purely via
``functional_call(layer, params, *args)``.  That bridge is what makes every
model jit/pjit-compilable while user code keeps the familiar stateful API
(``self.weight = self.create_parameter(...)``, ``state_dict()``,
``named_parameters()``...).

Key differences from the reference, by design:
- No C++ autograd tape: gradients come from ``jax.grad`` over
  ``functional_call`` (see paddle_tpu.autograd).
- Parameters are plain ``jax.Array``; metadata (trainable flag, partition
  spec for pjit/GSPMD sharding) lives beside them in the owning layer.
- Mutation during a traced forward is confined to trace time, so compiled
  steps are pure.
"""

from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import convert_dtype, get_default_dtype
from ..core import random as prandom
from . import initializer as I


_META_INIT = [False]


@contextlib.contextmanager
def meta_init():
    """Construct layers abstractly: parameters become
    ``jax.ShapeDtypeStruct`` leaves instead of materialised arrays
    (reference: ``paddle.LazyGuard`` — python/paddle/fluid/lazy_init.py).

    Use for AOT compilation/memory analysis of models that do not fit host
    RAM (``TrainStep.abstract_state`` + ``tools/memproof.py``).  A
    meta-constructed model cannot run eagerly; it can only be lowered."""
    _META_INIT[0] = True
    try:
        yield
    finally:
        _META_INIT[0] = False


class ParamMeta:
    """Per-parameter metadata kept outside the array itself."""

    __slots__ = ("trainable", "partition", "is_bias", "name_hint")

    def __init__(self, trainable=True, partition=None, is_bias=False, name_hint=None):
        self.trainable = trainable
        self.partition = partition  # jax.sharding.PartitionSpec or None
        self.is_bias = is_bias
        self.name_hint = name_hint


class Parameter:
    """Reference: the EagerParamBase/``paddle.nn.Parameter`` idiom —
    wrap an array so assigning it to a Layer attribute registers it as a
    (trainable) parameter:

        self.scale = nn.Parameter(jnp.ones((d,)))

    ``Layer.__setattr__`` unwraps it; the attribute then holds the plain
    array (jax arrays carry no identity, so the wrapper is consumed at
    assignment)."""

    __slots__ = ("data", "trainable")

    def __init__(self, data, trainable=True):
        import jax.numpy as _jnp
        self.data = _jnp.asarray(data)
        self.trainable = trainable


class ParamAttr:
    """``paddle.ParamAttr`` parity (subset: name/initializer/trainable)."""

    def __init__(self, name=None, initializer=None, trainable=True, learning_rate=1.0):
        self.name = name
        self.initializer = initializer
        self.trainable = trainable
        self.learning_rate = learning_rate


class ParameterList(list):
    """Return type of ``Layer.parameters()``: a list of arrays that also
    remembers the owning layer + flat names so optimizers can rebuild the
    name->array mapping (the reference passes Parameter objects that carry
    their own names; jax arrays cannot)."""

    def __init__(self, arrays, owner=None, names=None):
        super().__init__(arrays)
        self.owner = owner
        self.names = names or []


class Layer:
    """Base class for all neural network modules."""

    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        d = self.__dict__
        d["_parameters"] = OrderedDict()
        d["_param_meta"] = {}
        d["_buffers"] = OrderedDict()
        d["_non_persistable_buffers"] = set()
        d["_sub_layers"] = OrderedDict()
        d["_pending_params"] = {}
        d["_forward_pre_hooks"] = OrderedDict()
        d["_forward_post_hooks"] = OrderedDict()
        d["training"] = True
        d["_dtype"] = convert_dtype(dtype) if dtype is not None else get_default_dtype()
        d["_name_scope"] = name_scope or self.__class__.__name__.lower()

    # -- construction ------------------------------------------------------

    def create_parameter(self, shape, dtype=None, attr=None, is_bias=False,
                         default_initializer=None, partition=None, trainable=True):
        """Create (and stage) a parameter array.

        Mirrors ``Layer.create_parameter`` in the reference.  ``partition``
        is TPU-native extra metadata: a ``PartitionSpec`` over mesh axis
        names consumed by the pjit step-compiler to shard this parameter.
        """
        dtype = convert_dtype(dtype) if dtype is not None else self._dtype
        init = default_initializer
        if isinstance(attr, ParamAttr):
            init = attr.initializer or init
            trainable = attr.trainable and trainable
        if init is None:
            init = I.default_bias_init() if is_bias else I.default_weight_init()
        if not callable(init):
            raise TypeError("default_initializer must be callable")
        key = prandom.next_key("param_init")
        if _META_INIT[0]:
            # meta/abstract construction (paddle.LazyGuard analogue): record
            # shape+dtype only — no initializer runs, nothing materialises.
            # Enables AOT memory/compile analysis of models far larger than
            # host RAM (tools/memproof.py).
            value = jax.ShapeDtypeStruct(tuple(int(d) for d in shape),
                                         jnp.empty((), dtype).dtype)
        else:
            value = init(key, tuple(shape), dtype)
        meta = ParamMeta(trainable=trainable, partition=partition, is_bias=is_bias)
        # keyed by id but guarded by a weakref: a discarded staged param's id
        # can be recycled by CPython — the weakref identity check in
        # __setattr__ prevents misclassifying an unrelated array
        import weakref
        self._pending_params = {k: v for k, v in self._pending_params.items()
                                if v[0]() is not None}  # purge dead entries
        # ShapeDtypeStruct (meta_init) is not weakref-able; a strong ref is
        # fine there — structs are tiny and construction is short-lived
        ref = ((lambda v=value: v) if _META_INIT[0] else weakref.ref(value))
        self._pending_params[id(value)] = (ref, meta)
        return value

    def _register_parameter(self, name: str, value, meta: "ParamMeta"):
        """Register an already-materialised array as a parameter without
        drawing from the init RNG stream (used when hoisting/stacking
        existing parameters, e.g. pipeline stage stacking)."""
        self._parameters[name] = value
        self._param_meta[name] = meta
        object.__setattr__(self, name, value)
        return value

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffers.add(name)
        object.__setattr__(self, name, tensor)

    def add_sublayer(self, name: str, sublayer: "Layer"):
        setattr(self, name, sublayer)
        return sublayer

    def add_parameter(self, name: str, parameter):
        setattr(self, name, parameter)
        return parameter

    # -- attribute plumbing ------------------------------------------------

    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        if params is None:  # before __init__
            object.__setattr__(self, name, value)
            return
        if isinstance(value, Parameter):
            self._parameters[name] = value.data
            self._param_meta[name] = ParamMeta(trainable=value.trainable,
                                               name_hint=name)
            self._sub_layers.pop(name, None)
            object.__setattr__(self, name, value.data)
            return
        if isinstance(value, Layer):
            self._sub_layers[name] = value
            self._parameters.pop(name, None)
        elif id(value) in self._pending_params and \
                self._pending_params[id(value)][0]() is value:
            self._parameters[name] = value
            self._param_meta[name] = self._pending_params.pop(id(value))[1]
            self._sub_layers.pop(name, None)
        elif name in self._parameters:
            # re-assignment of an existing parameter (e.g. set_state_dict)
            self._parameters[name] = value
        elif name in self._buffers:
            self._buffers[name] = value
        object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        # only called when normal lookup fails
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    # -- traversal ---------------------------------------------------------

    def named_sublayers(self, prefix="", include_self=False) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            p = f"{prefix}.{name}" if prefix else name
            yield p, sub
            yield from sub.named_sublayers(prefix=p)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, jax.Array]]:
        for name, p in self._parameters.items():
            yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                sp = f"{prefix}.{sname}" if prefix else sname
                yield from sub.named_parameters(prefix=sp)

    def parameters(self, include_sublayers=True) -> ParameterList:
        items = list(self.named_parameters(include_sublayers=include_sublayers))
        return ParameterList([v for _, v in items], owner=self, names=[k for k, _ in items])

    def named_buffers(self, prefix="", include_sublayers=True, persistable_only=False):
        for name, b in self._buffers.items():
            if persistable_only and name in self._non_persistable_buffers:
                continue
            yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                sp = f"{prefix}.{sname}" if prefix else sname
                yield from sub.named_buffers(prefix=sp, persistable_only=persistable_only)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def param_meta(self) -> Dict[str, ParamMeta]:
        """Flat name -> ParamMeta for every parameter (used by the
        step-compiler for sharding and by optimizers for trainability)."""
        out = {}
        for path, sub in self.named_sublayers(include_self=True, prefix=""):
            for name, meta in sub._param_meta.items():
                out[f"{path}.{name}" if path else name] = meta
        return out

    # -- state dict --------------------------------------------------------

    def _raw_state_dict(self, include_sublayers=True,
                        structured_name_prefix="",
                        include_non_persistable_buffer=False) -> Dict[str, jax.Array]:
        """state_dict keyed by the REAL attribute paths — used internally by
        set_state_dict so subclasses that override state_dict() with name
        translation (e.g. the RNN reference-naming shim) don't break
        assignment."""
        out = OrderedDict()
        for k, v in self.named_parameters(prefix=structured_name_prefix,
                                          include_sublayers=include_sublayers):
            out[k] = v
        for k, v in self.named_buffers(prefix=structured_name_prefix,
                                       include_sublayers=include_sublayers,
                                       persistable_only=not include_non_persistable_buffer):
            out[k] = v
        return out

    def state_dict(self, include_sublayers=True, structured_name_prefix="",
                   include_non_persistable_buffer=False) -> Dict[str, jax.Array]:
        return self._raw_state_dict(include_sublayers,
                                    structured_name_prefix,
                                    include_non_persistable_buffer)

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name=True):
        own = self._raw_state_dict(include_non_persistable_buffer=True)
        missing, unexpected = [], []
        for k in own:
            if k not in state_dict:
                missing.append(k)
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            cur = own[k]
            v = jnp.asarray(v)
            if tuple(v.shape) != tuple(cur.shape):
                raise ValueError(f"shape mismatch for {k}: {v.shape} vs {cur.shape}")
            self._assign_by_path(k, v.astype(cur.dtype))
        return missing, unexpected

    load_dict = set_state_dict

    def _resolve_path(self, path: str) -> Tuple["Layer", str]:
        parts = path.split(".")
        layer = self
        for p in parts[:-1]:
            layer = layer._sub_layers[p]
        return layer, parts[-1]

    def _assign_by_path(self, path: str, value):
        layer, name = self._resolve_path(path)
        if name in layer._parameters:
            layer._parameters[name] = value
            object.__setattr__(layer, name, value)
        elif name in layer._buffers:
            layer._buffers[name] = value
            object.__setattr__(layer, name, value)
        else:
            raise KeyError(f"no parameter or buffer named {path!r}")

    # -- modes / apply -----------------------------------------------------

    def _extra_mode_layers(self):
        """Override point: extra layers (outside the sublayer registry,
        e.g. a stacked-parameter template) that must still follow
        train()/eval() mode switches."""
        return ()

    def _walk_mode_layers(self):
        yield self
        for l in self._sub_layers.values():
            yield from l._walk_mode_layers()
        for l in self._extra_mode_layers():
            yield from l._walk_mode_layers()

    def train(self):
        for l in self._walk_mode_layers():
            l.__dict__["training"] = True
        return self

    def eval(self):
        for l in self._walk_mode_layers():
            l.__dict__["training"] = False
        return self

    def apply(self, fn: Callable[["Layer"], None]):
        for _, l in self.named_sublayers(include_self=True):
            fn(l)
        return self

    def astype(self, dtype):
        """Cast floating-point parameters/buffers in place (``Layer.to`` subset)."""
        dtype = convert_dtype(dtype)
        for path, sub in self.named_sublayers(include_self=True, prefix=""):
            for name, p in list(sub._parameters.items()):
                if jnp.issubdtype(p.dtype, jnp.floating):
                    if isinstance(p, jax.ShapeDtypeStruct):
                        # meta_init() construction: cast abstractly
                        sub._parameters[name] = jax.ShapeDtypeStruct(
                            p.shape, jnp.empty((), dtype).dtype)
                    else:
                        sub._parameters[name] = p.astype(dtype)
                    object.__setattr__(sub, name, sub._parameters[name])
            for name, b in list(sub._buffers.items()):
                if hasattr(b, "dtype") and jnp.issubdtype(b.dtype, jnp.floating):
                    sub._buffers[name] = b.astype(dtype)
                    object.__setattr__(sub, name, sub._buffers[name])
            sub.__dict__["_dtype"] = dtype
        return self

    to = astype

    # -- hooks -------------------------------------------------------------

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- call --------------------------------------------------------------

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    def extra_repr(self):
        return ""

    def __repr__(self):
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"({name}): {sub_repr[0]}")
            lines.extend("  " + l for l in sub_repr[1:])
        extra = self.extra_repr()
        head = f"{type(self).__name__}({extra}" + (")" if not lines else "")
        if not lines:
            return head
        return head + "\n  " + "\n  ".join(lines) + "\n)"


class _HookHandle:
    _next_id = [0]

    def __init__(self, registry):
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        self._registry = registry

    def remove(self):
        self._registry.pop(self.id, None)


# ---------------------------------------------------------------------------
# functional bridge
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _swapped_params(layer: Layer, params: Dict[str, Any]):
    old = {}
    try:
        for k, v in params.items():
            sub, name = layer._resolve_path(k)
            old[k] = sub._parameters[name] if name in sub._parameters else sub._buffers[name]
            layer._assign_by_path(k, v)
        yield
    finally:
        for k, v in old.items():
            layer._assign_by_path(k, v)


@contextlib.contextmanager
def _train_mode(layer: Layer, training: Optional[bool]):
    if training is None:
        yield
        return
    prev = [(l, l.training) for l in layer._walk_mode_layers()]
    (layer.train() if training else layer.eval())
    try:
        yield
    finally:
        for l, t in prev:
            l.__dict__["training"] = t


def functional_call(layer: Layer, params: Optional[Dict[str, Any]], *args,
                    rngs: Optional[jax.Array] = None, training: Optional[bool] = None,
                    **kwargs):
    """Run ``layer`` as a pure function of ``params``.

    ``params`` maps flat dotted names (a subset is fine) to arrays; they are
    swapped in for the duration of the call and restored afterwards.  Swap
    happens at trace time, so under ``jax.jit`` the result is a fully pure
    compiled function.  ``rngs`` installs an explicit RNG stream (see
    core.random) so dropout &c. are deterministic in the step key.
    """
    params = params or {}
    with _swapped_params(layer, params), _train_mode(layer, training), \
            prandom.rng_scope(rngs):
        return layer(*args, **kwargs)


def raw_params(layer: Layer) -> Dict[str, jax.Array]:
    """Flat name->array dict of all parameters (the optimizer pytree).

    A plain dict (not OrderedDict) so its pytree type matches the dicts the
    optimizer/train-step build — jax treats dict and OrderedDict as distinct
    node types.
    """
    return dict(layer.named_parameters())


def serving_params(layer: Layer) -> Dict[str, jax.Array]:
    """Parameters PLUS array buffers — the inference-path pytree.

    Weight-only quantized layers (nn.quant.QuantizedLinear) keep their
    int8/int4 weights as buffers; passing them through functional_call as
    inputs (instead of closing over them) keeps compiled decode loops
    free of hundreds of MB of baked-in constants."""
    params = dict(layer.named_parameters())
    for name, buf in layer.named_buffers():
        if buf is not None and name not in params:
            params[name] = buf
    return params


def trainable_mask(layer: Layer) -> Dict[str, bool]:
    meta = layer.param_meta()
    return {k: meta[k].trainable for k in raw_params(layer)}
