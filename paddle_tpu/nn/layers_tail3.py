"""Round-3 nn layer tail (SURVEY §2.6 nn row).

Reference: python/paddle/nn/layer/{activation,pooling,loss,norm}.py
members not yet covered.  Thin Layer wrappers over the functional ops;
torch-oracle tests in tests/test_nn_tail3.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import functional as F
from .layer import Layer


def maxout(x, groups, axis=1, name=None):
    """Reference: paddle.nn.functional.maxout — max over ``groups``-sized
    chunks of the channel dim."""
    x = jnp.asarray(x)
    axis = axis % x.ndim
    c = x.shape[axis]
    if c % groups:
        raise ValueError(f"channels {c} not divisible by groups {groups}")
    shape = (x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:])
    return jnp.max(x.reshape(shape), axis=axis + 1)


F.maxout = maxout
F.__all__.append("maxout")   # F.__all__ is fixed at its module-exec end


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return maxout(x, self.groups, self.axis)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Softsign(Layer):
    def forward(self, x):
        return F.softsign(x)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        return F.lp_pool1d(x, *self.args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        return F.lp_pool2d(x, *self.args)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format)
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, *self.args,
                              output_size=self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format)
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, *self.args,
                              output_size=self.output_size)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool2d(x, *self.args)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool3d(x, *self.args)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = ([padding] * 2 if isinstance(padding, int)
                        else list(padding))
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, "constant", 0.0, self.data_format)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding = ([padding] * 6 if isinstance(padding, int)
                        else list(padding))
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, "constant", 0.0, self.data_format)


class SpectralNorm(Layer):
    """Reference: paddle.nn.SpectralNorm — standalone layer returning the
    spectrally-normalised WEIGHT (unlike nn.utils.spectral_norm, which
    hooks an existing layer's parameter)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.dim, self.power_iters, self.eps = dim, power_iters, eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from . import initializer as I
        self.weight_u = self.create_parameter(
            (h,), default_initializer=I.Normal(0.0, 1.0), trainable=False)
        self.weight_v = self.create_parameter(
            (w,), default_initializer=I.Normal(0.0, 1.0), trainable=False)

    def forward(self, weight):
        mat = jnp.moveaxis(weight, self.dim, 0).reshape(weight.shape[self.dim], -1)
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        sigma = u @ mat @ v
        return weight / sigma


def _loss_cls(name, fn, arg_names, defaults):
    def __init__(self, **kwargs):
        Layer.__init__(self)
        self.kwargs = {**defaults, **kwargs}

    def forward(self, *args):
        return fn(*args, **self.kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward,
                                 "__doc__": f"Reference: paddle.nn.{name} "
                                            f"(wraps F.{fn.__name__})"})


SoftMarginLoss = _loss_cls("SoftMarginLoss", F.soft_margin_loss, (),
                           {"reduction": "mean"})
MultiMarginLoss = _loss_cls("MultiMarginLoss", F.multi_margin_loss, (),
                            {"p": 1, "margin": 1.0, "weight": None,
                             "reduction": "mean"})
MultiLabelSoftMarginLoss = _loss_cls(
    "MultiLabelSoftMarginLoss", F.multi_label_soft_margin_loss, (),
    {"weight": None, "reduction": "mean"})
TripletMarginLoss = _loss_cls(
    "TripletMarginLoss", F.triplet_margin_loss, (),
    {"margin": 1.0, "p": 2, "swap": False, "reduction": "mean"})
TripletMarginWithDistanceLoss = _loss_cls(
    "TripletMarginWithDistanceLoss", F.triplet_margin_with_distance_loss,
    (), {"distance_function": None, "margin": 1.0, "swap": False,
         "reduction": "mean"})
CosineEmbeddingLoss = _loss_cls(
    "CosineEmbeddingLoss", F.cosine_embedding_loss, (),
    {"margin": 0.0, "reduction": "mean"})
HingeEmbeddingLoss = _loss_cls(
    "HingeEmbeddingLoss", F.hinge_embedding_loss, (),
    {"margin": 1.0, "reduction": "mean"})
PoissonNLLLoss = _loss_cls(
    "PoissonNLLLoss", F.poisson_nll_loss, (),
    {"log_input": True, "full": False, "epsilon": 1e-8,
     "reduction": "mean"})
GaussianNLLLoss = _loss_cls(
    "GaussianNLLLoss", F.gaussian_nll_loss, (),
    {"full": False, "epsilon": 1e-6, "reduction": "mean"})
CTCLoss = _loss_cls("CTCLoss", F.ctc_loss, (),
                    {"blank": 0, "reduction": "mean"})
RNNTLoss = _loss_cls("RNNTLoss", F.rnnt_loss, (),
                     {"blank": 0, "fastemit_lambda": 0.0,
                      "reduction": "mean"})
