"""Gradient clipping (``paddle.nn.ClipGradByGlobalNorm`` etc.).

Reference: python/paddle/nn/clip.py.  Clips act on a flat grad pytree inside
the compiled step.  ``ClipGradByGlobalNorm`` is hybrid-parallel aware the
same way the reference's HybridParallelOptimizer makes it: when gradients
are sharded over mesh axes, the local sum-of-squares is psum-ed over those
axes before the norm is formed (see distributed.fleet.HybridParallelOptimizer
which passes ``axes`` here).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, grads: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return jax.tree.map(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    """Per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip_one(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g * scale).astype(g.dtype)
        return jax.tree.map(clip_one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0, group_name="default_group",
                 sum_axes: Optional[Sequence[str]] = None):
        self.clip_norm = clip_norm
        # mesh axes over which grads are *partitioned* (not replicated);
        # local sq-sums must be summed over them for a correct global norm
        self.sum_axes = tuple(sum_axes or ())

    def with_axes(self, axes: Sequence[str]) -> "ClipGradByGlobalNorm":
        return ClipGradByGlobalNorm(self.clip_norm, sum_axes=axes)

    def global_norm(self, grads) -> jax.Array:
        leaves = jax.tree.leaves(grads)
        sq = jnp.asarray(0.0, jnp.float32)
        for g in leaves:
            sq = sq + jnp.sum(jnp.square(g.astype(jnp.float32)))
        for ax in self.sum_axes:
            try:
                sq = jax.lax.psum(sq, ax)
            except NameError:
                pass  # axis not bound (serial execution of the same code)
        return jnp.sqrt(sq)

    def __call__(self, grads):
        norm = self.global_norm(grads)
        scale = self.clip_norm / jnp.maximum(norm, self.clip_norm)
        return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)
