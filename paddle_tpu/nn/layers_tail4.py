"""Round-4 nn layer tail: 3-D pools/norms/convs, remaining activation
classes, HSigmoidLoss, single-cell RNN wrapper, BeamSearchDecoder +
dynamic_decode.

Reference: python/paddle/nn/layer/{pooling,norm,conv,activation,loss,rnn}.py
(SURVEY §2.6 layers row).  Tests: tests/test_nn_tail4.py.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from . import functional as F
from . import initializer as I
from .layer import Layer, functional_call


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW"):
        super().__init__()
        self.output_size, self.data_format = output_size, data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, data_format="NCDHW"):
        super().__init__()
        self.output_size, self.data_format = output_size, data_format
        if return_mask:
            raise NotImplementedError(
                "AdaptiveMaxPool3D(return_mask=True): 3-D argmax masks are "
                "not implemented (1-D has them via F.adaptive_max_pool1d); "
                "raising rather than silently dropping the mask")

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.data_format)


# ---------------------------------------------------------------------------
# norms / convs (BatchNorm/InstanceNorm cores are ndim-agnostic — the 3-D
# classes pin the expected rank for shape checking and API parity)
# ---------------------------------------------------------------------------

from .layers_common import BatchNorm2D  # noqa: E402
from .layers_conv import InstanceNorm2D  # noqa: E402


class BatchNorm3D(BatchNorm2D):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCDHW"
                         else "NHWC", name)


class BatchNorm(BatchNorm2D):
    """Reference: the 1.x-style paddle.nn.BatchNorm (channel axis 1, an
    optional fused activation)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class InstanceNorm3D(InstanceNorm2D):
    pass


class Conv1DTranspose(Layer):
    """Weight layout (in_c, out_c/groups, k) per the reference."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        self.stride, self.padding, self.output_padding = \
            stride, padding, output_padding
        self.dilation, self.groups, self.data_format = \
            dilation, groups, data_format
        fan_in = in_channels * k // groups
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, k), attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = None
        if bias_attr is not False:
            bound = 1 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))

    def forward(self, x):
        return F.conv1d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.dilation, self.groups,
            self.data_format)


class Conv3DTranspose(Layer):
    """Weight layout (in_c, out_c/groups, kd, kh, kw) per the reference."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.output_padding = \
            stride, padding, output_padding
        self.dilation, self.groups, self.data_format = \
            dilation, groups, data_format
        fan_in = in_channels * k[0] * k[1] * k[2] // groups
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *k), attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = None
        if bias_attr is not False:
            bound = 1 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))

    def forward(self, x):
        return F.conv3d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.dilation, self.groups,
            self.data_format)


# ---------------------------------------------------------------------------
# activation classes
# ---------------------------------------------------------------------------

class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class GumbelSoftmax(Layer):
    def __init__(self, temperature=1.0, hard=False, axis=-1, name=None):
        super().__init__()
        self.temperature, self.hard, self.axis = temperature, hard, axis

    def forward(self, x):
        return F.gumbel_softmax(x, self.temperature, self.hard, self.axis)


# ---------------------------------------------------------------------------
# hierarchical sigmoid
# ---------------------------------------------------------------------------

class HSigmoidLoss(Layer):
    """Reference: paddle.nn.HSigmoidLoss — holds the internal-node weight
    table ((num_classes-1, feature) for the default complete tree)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("HSigmoidLoss: num_classes must be >= 2")
        self.num_classes = num_classes
        self.is_custom = is_custom
        rows = num_classes if is_custom else num_classes - 1
        bound = 1.0 / math.sqrt(feature_size)
        self.weight = self.create_parameter(
            (rows, feature_size), attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (rows,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code)


# ---------------------------------------------------------------------------
# single-cell RNN wrapper + beam-search decoding
# ---------------------------------------------------------------------------

class RNN(Layer):
    """Reference: paddle.nn.RNN — scan one cell over time.

    forward(inputs, initial_states=None, sequence_length=None)
      → (outputs, final_states); inputs (B, T, F) unless time_major.
    """

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse, self.time_major = is_reverse, time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)
        B = x.shape[1]
        if initial_states is None:
            if hasattr(self.cell, "get_initial_states"):
                initial_states = self.cell.get_initial_states(B)
            else:
                h = jnp.zeros((B, self.cell.hidden_size))
                initial_states = (h, jnp.zeros_like(h)) \
                    if "LSTM" in type(self.cell).__name__ else h
        params = dict(self.cell.named_parameters())
        ts = jnp.arange(x.shape[0])

        def step(state, inp):
            xt, t = inp
            new = functional_call(self.cell, params, xt, state)
            if sequence_length is not None:
                valid = (t < sequence_length)[:, None]
                new = jax.tree.map(
                    lambda n, o: jnp.where(valid, n, o), new, state)
            h = new[0] if isinstance(new, tuple) else new
            if sequence_length is not None:
                h = jnp.where((t < sequence_length)[:, None], h, 0.0)
            return new, h

        final, ys = jax.lax.scan(step, initial_states, (x, ts),
                                 reverse=self.is_reverse)
        out = ys if self.time_major else jnp.swapaxes(ys, 0, 1)
        return out, final


class BeamSearchDecoder:
    """Reference: paddle.nn.BeamSearchDecoder — beam decoding around an
    RNN cell with an embedding fn and an output (vocab projection) fn.

    TPU-native formulation: the whole decode is ONE ``lax.scan`` inside
    ``dynamic_decode`` (fixed ``max_step_num`` trip count, finished-beam
    masking) instead of the reference's per-step dynamic loop, so it
    compiles once and runs on-chip.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token, self.end_token = int(start_token), int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers over (B*K, ...) flattened beam states ---------------------

    def _tile(self, tree, K):
        return jax.tree.map(
            lambda t: jnp.repeat(t, K, axis=0), tree)

    def _gather_beams(self, tree, parent, B, K):
        # parent: (B, K) beam index per slot → flat (B*K,) row gather
        flat = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
        return jax.tree.map(lambda t: t[flat], tree)


def dynamic_decode(decoder, inits=None, max_step_num=20,
                   output_time_major=False, **kwargs):
    """Reference: paddle.nn.dynamic_decode.  Returns (predicted_ids,
    final_cell_states); predicted_ids (B, T, beam) (or time-major),
    finalized through F.gather_tree so each beam carries its full ancestry.

    The decode is one compiled ``lax.scan`` of ``max_step_num`` steps;
    beams that emit ``end_token`` are frozen (their score stops changing
    and they keep emitting ``end_token``).
    """
    cell = decoder.cell
    K = decoder.beam_size
    params = dict(cell.named_parameters())

    if inits is None:
        raise ValueError("dynamic_decode: pass inits (initial cell states, "
                         "batch-major) — e.g. encoder final states")
    B = jax.tree.leaves(inits)[0].shape[0]
    states = decoder._tile(inits, K)                      # (B*K, ...)

    tokens0 = jnp.full((B * K,), decoder.start_token, jnp.int32)
    # lane 0 active, lanes 1.. start at -inf so step 1 expands one beam
    lp0 = jnp.where(jnp.arange(K) == 0, 0.0, -1e9)
    log_probs0 = jnp.broadcast_to(lp0[None, :], (B, K))
    finished0 = jnp.zeros((B, K), bool)

    def embed(tok):
        if decoder.embedding_fn is not None:
            return decoder.embedding_fn(tok)
        return jax.nn.one_hot(tok, getattr(cell, "input_size"))

    def step(carry, _):
        tokens, states, log_probs, finished = carry
        x = embed(tokens)
        new_states = functional_call(cell, params, x, states)
        h = new_states[0] if isinstance(new_states, tuple) else new_states
        logits = decoder.output_fn(h) if decoder.output_fn is not None else h
        V = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        step_lp = step_lp.reshape(B, K, V)
        # finished beams: only end_token continues, at no cost
        eos_row = jnp.full((V,), -1e9).at[decoder.end_token].set(0.0)
        step_lp = jnp.where(finished[:, :, None], eos_row[None, None, :],
                            step_lp)
        total = log_probs[:, :, None] + step_lp                # (B, K, V)
        flat = total.reshape(B, K * V)
        new_lp, idx = jax.lax.top_k(flat, K)                   # (B, K)
        parent = idx // V
        token = (idx % V).astype(jnp.int32)
        new_states = decoder._gather_beams(new_states, parent, B, K)
        new_finished = jnp.take_along_axis(finished, parent, axis=1) | \
            (token == decoder.end_token)
        carry = (token.reshape(-1), new_states, new_lp, new_finished)
        return carry, (token, parent.astype(jnp.int32))

    (tokens, states, log_probs, finished), (ids, parents) = jax.lax.scan(
        step, (tokens0, states, log_probs0, finished0), None,
        length=max_step_num)
    # ids/parents: (T, B, K) → ancestry-resolved sequences
    seqs = F.gather_tree(ids, parents)
    if not output_time_major:
        seqs = jnp.transpose(seqs, (1, 0, 2))                  # (B, T, K)
    return seqs, states
