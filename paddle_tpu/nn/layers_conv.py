"""Conv/pool/vision layer zoo breadth (reference: python/paddle/nn/layer/
conv.py, pooling.py, norm.py InstanceNorm*, vision.py PixelShuffle).

All convs lower to jax.lax.conv_general_dilated → XLA conv → MXU.
"""

from __future__ import annotations

import math

from . import functional as F
from . import initializer as I
from .layer import Layer


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


class Conv1D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        self.stride, self.padding, self.dilation, self.groups = \
            stride, padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k // groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, k), attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = None
        if bias_attr is not False:
            bound = 1 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = \
            stride, padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k[0] * k[1] * k[2] // groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *k), attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = None
        if bias_attr is not False:
            bound = 1 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2DTranspose(Layer):
    """Weight layout (in_c, out_c/groups, kh, kw) per the reference."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = _pair(kernel_size)
        self.stride, self.padding, self.output_padding = \
            stride, padding, output_padding
        self.dilation, self.groups, self.data_format = \
            dilation, groups, data_format
        fan_in = in_channels * k[0] * k[1] // groups
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *k), attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = None
        if bias_attr is not False:
            bound = 1 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))

    def forward(self, x):
        return F.conv2d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.dilation, self.groups, self.data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = self.bias = None
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self.epsilon,
                               self.data_format)


class InstanceNorm1D(InstanceNorm2D):
    def __init__(self, num_features, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(num_features, epsilon, weight_attr, bias_attr,
                         data_format)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.data_format)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW"):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW"):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        w = self.weight
        if w.shape[0] > 1 and x.ndim > 1:
            shape = [1] * x.ndim
            shape[1 if self.data_format.startswith("NC") else -1] = -1
            w = w.reshape(shape)
        return F.prelu(x, w)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW"):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW"):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, self.training, self.data_format)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)
