"""``paddle_tpu.nn`` — module system + layer zoo + functional ops."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import (Layer, ParamAttr, ParameterList, functional_call,  # noqa: F401
                    raw_params, trainable_mask)
from .layers_common import (  # noqa: F401
    AvgPool2D, BatchNorm1D, BatchNorm2D, BCEWithLogitsLoss, Conv2D,
    CrossEntropyLoss, Dropout, Embedding, Flatten, GELU, GroupNorm,
    Hardsigmoid, Hardswish, Identity, L1Loss, LayerDict, LayerList,
    LayerNorm, LeakyReLU, Linear, MaxPool2D, Mish, MSELoss,
    MultiHeadAttention, NLLLoss, ReLU, RMSNorm, Sequential, Sigmoid, Silu,
    Softmax, Softplus, Tanh, TransformerEncoder, TransformerEncoderLayer,
    Upsample)
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
