"""``paddle_tpu.nn`` — module system + layer zoo + functional ops."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from . import utils  # noqa: F401
from .layer import (Layer, ParamAttr, Parameter, ParameterList, functional_call,  # noqa: F401
                    meta_init, raw_params, trainable_mask)
from .layers_common import (  # noqa: F401
    AvgPool2D, BatchNorm1D, BatchNorm2D, BCEWithLogitsLoss, Conv2D,
    CrossEntropyLoss, Dropout, Embedding, Flatten, GELU, GroupNorm,
    Hardsigmoid, Hardswish, Identity, L1Loss, LayerDict, LayerList,
    LayerNorm, LeakyReLU, Linear, MaxPool2D, Mish, MSELoss,
    MultiHeadAttention, NLLLoss, ReLU, RMSNorm, Sequential, Sigmoid, Silu,
    Softmax, Softplus, Tanh, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
    Upsample)
from .layers_conv import (  # noqa: F401
    AdaptiveAvgPool2D, AdaptiveMaxPool2D, AvgPool1D, BCELoss, Conv1D,
    Conv2DTranspose, Conv3D, CosineSimilarity, Dropout2D, InstanceNorm1D,
    InstanceNorm2D, KLDivLoss, MarginRankingLoss, MaxPool1D, Pad2D,
    PixelShuffle, PixelUnshuffle, PReLU, SmoothL1Loss)
from .layers_rnn import (  # noqa: F401
    GRU, GRUCell, LSTM, LSTMCell, RNNCellBase, SimpleRNN, SimpleRNNCell)
from .layers_tail4 import (  # noqa: F401
    RNN, AdaptiveAvgPool1D, AdaptiveAvgPool3D, AdaptiveMaxPool3D,
    AvgPool3D, BatchNorm, BatchNorm3D, BeamSearchDecoder, Conv1DTranspose,
    Conv3DTranspose, ELU, GumbelSoftmax, Hardtanh, HSigmoidLoss,
    InstanceNorm3D, MaxPool3D, ReLU6, dynamic_decode)
from .layers_more import (  # noqa: F401
    AdaptiveMaxPool1D, AlphaDropout, Bilinear, CELU, ChannelShuffle,
    Dropout3D, FeatureAlphaDropout, Fold, GLU, Hardshrink,
    LocalResponseNorm, LogSigmoid, MaxUnPool2D, Pad1D, Pad3D,
    PairwiseDistance, SELU, Softmax2D, Softshrink, SyncBatchNorm,
    Tanhshrink, ThresholdedReLU, Unflatten, Unfold,
    UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D)
from .layers_tail3 import (  # noqa: F401
    CTCLoss, CosineEmbeddingLoss, FractionalMaxPool2D, FractionalMaxPool3D,
    GaussianNLLLoss, HingeEmbeddingLoss, LPPool1D, LPPool2D, LogSoftmax,
    MaxUnPool1D, MaxUnPool3D, Maxout, MultiLabelSoftMarginLoss,
    MultiMarginLoss, PoissonNLLLoss, RNNTLoss, RReLU, SoftMarginLoss,
    Softsign, SpectralNorm, TripletMarginLoss,
    TripletMarginWithDistanceLoss, ZeroPad1D, ZeroPad3D)
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401

SiLU = Silu  # reference spells it both ways across versions
