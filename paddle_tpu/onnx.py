"""paddle_tpu.onnx — export facade.

Reference: python/paddle/onnx/export.py (paddle2onnx bridge).  ONNX
export is a documented de-scope (SURVEY §7.3): the TPU serving format is
AOT StableHLO (``paddle_tpu.jit.save`` → ``inference.Predictor``), which
is what XLA consumes natively.  ``export`` writes that artifact when
given a path and raises with the migration pointer when a real .onnx
file is demanded.
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Reference signature: paddle.onnx.export(layer, path, input_spec).

    Writes the portable AOT artifact (StableHLO via jit.save) at
    ``path``; a strict ``.onnx`` protobuf is out of scope on TPU — see
    docs/MIGRATION.md §serving for the Predictor path.
    """
    if str(path).endswith(".onnx"):
        raise NotImplementedError(
            "ONNX protobuf emission is de-scoped on TPU (SURVEY §7.3): "
            "export with jit.save → StableHLO and serve via "
            "paddle_tpu.inference.Predictor; docs/MIGRATION.md §serving")
    from . import jit
    return jit.save(layer, path, input_spec=input_spec)
