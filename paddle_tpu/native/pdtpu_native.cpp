// pdtpu_native: C++ runtime components for paddle_tpu.
//
// Reference parity (SURVEY §2.4/§2.6): the reference implements its
// rendezvous store (paddle/fluid/distributed/store/tcp_store.cc), reader
// blocking queue (paddle/fluid/operators/reader/ + blocking_queue.h), and
// batch collation in C++. These are their TPU-host equivalents:
//
//   1. TCPStore server — same length-prefixed wire protocol as the Python
//      client in paddle_tpu/launch/store.py (u32 nfields, then per field
//      u32 len + bytes). Runs the rendezvous/elastic-heartbeat store
//      without ever touching the training process's GIL.
//   2. BlockingQueue — bounded MPMC queue of byte blocks (the reference's
//      reader blocking queue role) for the DataLoader prefetch pipeline.
//   3. collate_stack — batched memcpy (np.stack equivalent) callable with
//      the GIL released, so a DataLoader thread pool actually scales.
//
// Built with: g++ -O2 -fPIC -shared -pthread -o libpdtpu_native.so
// No Python.h dependency — pure C ABI consumed via ctypes.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// wire helpers (protocol shared with paddle_tpu/launch/store.py)
// ---------------------------------------------------------------------------

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// u32 little-endian on the wire (struct '<I' on the Python side) —
// explicit conversion keeps the protocol byte-order portable
uint32_t le32_decode(const void* p) {
  const unsigned char* b = static_cast<const unsigned char*>(p);
  return static_cast<uint32_t>(b[0]) | (static_cast<uint32_t>(b[1]) << 8) |
         (static_cast<uint32_t>(b[2]) << 16) |
         (static_cast<uint32_t>(b[3]) << 24);
}

void le32_encode(uint32_t v, std::string* out) {
  char b[4] = {static_cast<char>(v & 0xff), static_cast<char>((v >> 8) & 0xff),
               static_cast<char>((v >> 16) & 0xff),
               static_cast<char>((v >> 24) & 0xff)};
  out->append(b, 4);
}

bool read_msg(int fd, std::vector<std::string>* fields) {
  char nf_raw[4];
  if (!read_exact(fd, nf_raw, 4)) return false;
  uint32_t nf = le32_decode(nf_raw);
  if (nf > 1024) return false;  // sanity bound
  fields->clear();
  for (uint32_t i = 0; i < nf; ++i) {
    char len_raw[4];
    if (!read_exact(fd, len_raw, 4)) return false;
    uint32_t len = le32_decode(len_raw);
    if (len > (64u << 20)) return false;  // 64 MiB per field bound
    std::string f(len, '\0');
    if (len && !read_exact(fd, &f[0], len)) return false;
    fields->push_back(std::move(f));
  }
  return true;
}

bool write_msg(int fd, const std::vector<std::string>& fields) {
  std::string out;
  le32_encode(static_cast<uint32_t>(fields.size()), &out);
  for (const auto& f : fields) {
    le32_encode(static_cast<uint32_t>(f.size()), &out);
    out.append(f);
  }
  return write_all(fd, out.data(), out.size());
}

// ---------------------------------------------------------------------------
// TCPStore server
// ---------------------------------------------------------------------------

class StoreServer {
 public:
  StoreServer() = default;

  int Start(const char* host, int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    if (host && *host) {
      // hostname or dotted quad — resolve like Python's socket.bind does
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (::getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
        ::close(listen_fd_);
        listen_fd_ = -1;
        return -1;
      }
      addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      ::freeaddrinfo(res);
    }
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) < 0 ||
        ::listen(listen_fd_, 128) < 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return -1;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    bound_port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return bound_port_;
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stopping_ = true;
      cv_.notify_all();
    }
    // shutdown unblocks accept(); the fd is CLOSED only after the accept
    // thread joins, so a racing accept() can never hit a reused fd number
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (accept_thread_.joinable()) accept_thread_.join();
    if (listen_fd_ >= 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    // Unblock workers parked in recv() on live client connections BEFORE
    // joining, or Stop would hang until every remote peer disconnects.
    {
      std::lock_guard<std::mutex> lk(workers_mu_);
      for (int fd : live_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    std::vector<std::thread> workers;
    {
      std::lock_guard<std::mutex> lk(workers_mu_);
      workers.swap(workers_);
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }

  ~StoreServer() { Stop(); }

 private:
  void AcceptLoop() {
    while (true) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;  // closed
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(workers_mu_);
      // reap finished workers so a long-lived server doesn't accumulate
      // one joinable thread (and its retained stack) per past connection
      for (auto it = workers_.begin(); it != workers_.end();) {
        if (done_ids_.count(it->get_id())) {
          it->join();
          done_ids_.erase(it->get_id());
          it = workers_.erase(it);
        } else {
          ++it;
        }
      }
      live_fds_.insert(fd);
      workers_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    std::vector<std::string> req;
    while (read_msg(fd, &req)) {
      if (req.empty()) break;
      std::vector<std::string> resp;
      try {
        resp = Dispatch(req);
      } catch (const std::exception&) {
        // malformed field (e.g. add on a non-numeric value): fail THIS
        // request, keep the server alive — matches the Python server where
        // socketserver contains per-connection exceptions
        resp = {"error"};
      }
      if (!write_msg(fd, resp)) break;
    }
    ::close(fd);
    std::lock_guard<std::mutex> lk(workers_mu_);
    live_fds_.erase(fd);
    done_ids_.insert(std::this_thread::get_id());
  }

  std::vector<std::string> Dispatch(const std::vector<std::string>& req) {
    const std::string& op = req[0];
    static const std::map<std::string, size_t> kArity = {
        {"set", 3}, {"get", 2}, {"add", 3}, {"delete", 2},
        {"cas", 4}, {"list", 2}, {"wait", 3}};
    auto ar = kArity.find(op);
    if (ar != kArity.end() && req.size() < ar->second)
      throw std::out_of_range("short store message");
    std::unique_lock<std::mutex> lk(mu_);
    if (op == "set") {
      kv_[req[1]] = req[2];
      cv_.notify_all();
      return {"ok"};
    }
    if (op == "get") {
      auto it = kv_.find(req[1]);
      if (it == kv_.end()) return {"miss"};
      return {"ok", it->second};
    }
    if (op == "add") {
      long long cur = 0;
      auto it = kv_.find(req[1]);
      if (it != kv_.end()) cur = std::stoll(it->second);
      cur += std::stoll(req[2]);
      kv_[req[1]] = std::to_string(cur);
      cv_.notify_all();
      return {"ok", std::to_string(cur)};
    }
    if (op == "delete") {
      bool existed = kv_.erase(req[1]) > 0;
      cv_.notify_all();
      return {existed ? "ok" : "miss"};
    }
    if (op == "cas") {
      auto it = kv_.find(req[1]);
      bool match = (it == kv_.end() && req[2].empty()) ||
                   (it != kv_.end() && it->second == req[2]);
      if (match) {
        kv_[req[1]] = req[3];
        cv_.notify_all();
        return {"ok", req[3]};
      }
      return {"miss", it == kv_.end() ? std::string() : it->second};
    }
    if (op == "list") {
      std::vector<std::string> out{"ok"};
      for (const auto& p : kv_)
        if (p.first.rfind(req[1], 0) == 0) out.push_back(p.first);
      return out;
    }
    if (op == "wait") {
      double timeout_s = std::stod(req[2]);
      auto deadline = std::chrono::steady_clock::now() +
                      std::chrono::duration<double>(timeout_s);
      while (kv_.find(req[1]) == kv_.end() && !stopping_) {
        if (cv_.wait_until(lk, deadline) == std::cv_status::timeout)
          return {"timeout"};
      }
      auto it = kv_.find(req[1]);
      if (it == kv_.end()) return {"timeout"};
      return {"ok", it->second};
    }
    return {"badop"};
  }

  int listen_fd_ = -1;
  int bound_port_ = -1;
  bool stopping_ = false;
  std::thread accept_thread_;
  std::vector<std::thread> workers_;
  std::set<int> live_fds_;
  std::set<std::thread::id> done_ids_;
  std::mutex workers_mu_;
  std::map<std::string, std::string> kv_;
  std::mutex mu_;
  std::condition_variable cv_;
};

// ---------------------------------------------------------------------------
// BlockingQueue of byte blocks
// ---------------------------------------------------------------------------

struct Block {
  char* data;
  size_t size;
};

class BlockingQueue {
 public:
  explicit BlockingQueue(size_t capacity) : capacity_(capacity) {}

  ~BlockingQueue() {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& b : q_) ::free(b.data);
    q_.clear();
  }

  // returns 0 on success, -1 on timeout, -2 if closed
  int Push(const char* data, size_t size, double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    while (q_.size() >= capacity_ && !closed_) {
      if (not_full_.wait_until(lk, deadline) == std::cv_status::timeout)
        return -1;
    }
    if (closed_) return -2;
    // malloc(1) floor: a non-null pointer even for empty payloads, so Pop's
    // nullptr return unambiguously means timeout/closed
    char* copy = static_cast<char*>(::malloc(size ? size : 1));
    if (!copy) return -3;  // out of host memory — surface, don't segfault
    if (size) ::memcpy(copy, data, size);
    q_.push_back({copy, size});
    not_empty_.notify_one();
    return 0;
  }

  // returns malloc'd block (caller frees via pdtpu_block_free); nullptr on
  // timeout/closed-empty. *size receives the length.
  char* Pop(size_t* size, double timeout_s, int* status) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s);
    while (q_.empty() && !closed_) {
      if (not_empty_.wait_until(lk, deadline) == std::cv_status::timeout) {
        *status = -1;
        return nullptr;
      }
    }
    if (q_.empty()) {  // closed and drained
      *status = -2;
      return nullptr;
    }
    Block b = q_.front();
    q_.pop_front();
    not_full_.notify_one();
    *size = b.size;
    *status = 0;
    return b.data;
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  size_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

 private:
  size_t capacity_;
  bool closed_ = false;
  std::deque<Block> q_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* pdtpu_store_server_create() { return new StoreServer(); }

int pdtpu_store_server_start(void* h, const char* host, int port) {
  return static_cast<StoreServer*>(h)->Start(host, port);
}

void pdtpu_store_server_destroy(void* h) {
  delete static_cast<StoreServer*>(h);
}

void* pdtpu_queue_create(size_t capacity) {
  return new BlockingQueue(capacity);
}

int pdtpu_queue_push(void* h, const char* data, size_t size,
                     double timeout_s) {
  return static_cast<BlockingQueue*>(h)->Push(data, size, timeout_s);
}

char* pdtpu_queue_pop(void* h, size_t* size, double timeout_s, int* status) {
  return static_cast<BlockingQueue*>(h)->Pop(size, timeout_s, status);
}

void pdtpu_queue_close(void* h) { static_cast<BlockingQueue*>(h)->Close(); }

size_t pdtpu_queue_size(void* h) {
  return static_cast<BlockingQueue*>(h)->Size();
}

void pdtpu_queue_destroy(void* h) { delete static_cast<BlockingQueue*>(h); }

void pdtpu_block_free(char* p) { ::free(p); }

// Stack n equal-sized sample buffers into dst (the np.stack hot path).
// Called through ctypes ⇒ GIL is released for the whole copy.
void pdtpu_collate_stack(char* dst, const char** srcs, size_t n,
                         size_t sample_bytes) {
  for (size_t i = 0; i < n; ++i)
    ::memcpy(dst + i * sample_bytes, srcs[i], sample_bytes);
}

}  // extern "C"
