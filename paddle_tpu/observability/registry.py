"""Thread-safe metrics registry: counters, gauges, histograms.

Reference capability (SURVEY §5.5): PaddlePaddle's monitor/profiler stack
keeps always-on runtime statistics next to training; here the registry is
the in-process store every telemetry producer (StepMonitor, the recompile
sentinel, collective accounting) writes through, and sinks snapshot.

Design constraints:

- Pure stdlib — importing this module must stay featherweight so the
  hot-path modules (jit, distributed.communication, launch.preempt) can
  reference the hook containers without dragging jax in.
- One registry lock guards metric *creation*; each metric carries its own
  lock for updates (a counter ``inc`` never contends with an unrelated
  histogram ``observe``).
- Histograms keep a bounded ring of recent observations (default 512) so
  p50/p95 are rolling, not lifetime — a regression shows up in the next
  snapshot instead of being averaged away by an hour of healthy steps.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Optional, Union

from .aggregate import HistogramSketch

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry"]


class Counter:
    """Monotonic counter (calls, bytes, compiles...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: Union[int, float] = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> Union[int, float]:
        return self._value

    def snapshot(self):
        return self._value


class Gauge:
    """Last-written value (queue depth, HBM highwater, lr...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = None
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return self._value


class Histogram:
    """Rolling histogram over the last ``window`` observations.

    ``count``/``sum`` are lifetime; ``percentile`` and the snapshot's
    p50/p95 cover only the ring, so they track the *current* regime.
    Percentile convention: nearest-rank (``ceil(p/100 * n)``-th smallest),
    the same convention tools/telemetry_report.py applies offline.

    ``sketch`` is the histogram's lifetime fleet-mergeable shadow
    (:class:`~paddle_tpu.observability.aggregate.HistogramSketch`):
    fixed log-spaced buckets a controller can merge across workers so
    the fleet p95 is computed from merged counts, never from averaged
    per-worker p95s.  The ring cannot serve that role — two rings merge
    into neither worker's distribution.
    """

    __slots__ = ("name", "_ring", "_count", "_sum", "_max", "_lock",
                 "sketch")

    def __init__(self, name: str, window: int = 512):
        self.name = name
        self._ring: deque = deque(maxlen=window)
        self._count = 0
        self._sum = 0.0
        self._max = None
        self._lock = threading.Lock()
        self.sketch = HistogramSketch()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._ring.append(v)
            self._count += 1
            self._sum += v
            if self._max is None or v > self._max:
                self._max = v
        self.sketch.observe(v)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, p: float) -> Optional[float]:
        with self._lock:
            data = sorted(self._ring)
        if not data:
            return None
        rank = max(1, math.ceil(p / 100.0 * len(data)))
        return data[min(rank, len(data)) - 1]

    def snapshot(self) -> dict:
        with self._lock:
            data = sorted(self._ring)
            count, total, mx = self._count, self._sum, self._max
        out = {"count": count, "sum": round(total, 6)}
        if data:
            def _pick(p):
                return data[max(1, math.ceil(p / 100.0 * len(data))) - 1]
            out.update(mean=round(total / max(count, 1), 6),
                       p50=_pick(50), p95=_pick(95), max=mx)
        return out


_KINDS = {"counter": Counter, "gauge": Gauge}


class MetricsRegistry:
    """Name → metric store; metrics are created on first use.

    A name is bound to one kind for the registry's lifetime — asking for
    ``counter("x")`` after ``gauge("x")`` raises instead of silently
    aliasing two semantics onto one series.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, window: int = 512) -> Histogram:
        return self._get(name, Histogram, window=window)

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, object]:
        """{name: value | histogram-summary} for the metrics event."""
        with self._lock:
            items = list(self._metrics.items())
        return {n: m.snapshot() for n, m in sorted(items)}
