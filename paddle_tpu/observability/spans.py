"""Trace spans: one vocabulary for the always-on JSONL stream AND the
deep-dive chrome trace.

``with span("ckpt.save"):`` feeds, depending on what is enabled:

- the **flight recorder**: a ``span_begin`` breadcrumb at entry (the
  liveness beat the hang watchdog polls — recorded BEFORE the body so a
  span that never returns is visible as a stuck name, not silence);
- the **registry**: a ``span[<name>].ms`` duration histogram;
- the **event stream**: one ``span`` JSONL event on exit;
- the **profiler**: while a ``paddle_tpu.profiler.Profiler`` is active,
  the span opens a ``RecordEvent`` so the same name lands on the host
  timeline of the chrome-trace export (and, via ``jax.named_scope``,
  inside the device trace).

Pre-instrumented sites: ``jit.TrainStep`` steps (via StepMonitor, as
``emit=False`` spans — the ``step`` event already carries the numbers),
``distributed.Engine.fit`` / ``hapi.Model.fit`` epochs, ``ckpt``
save/load, eager collectives, and ``jit.save``/``jit.load`` AOT export.

Disabled cost: one falsy check on the ``_state.SPAN`` hook plus one
falsy check on the profiler's active list — no imports, no clock reads
beyond ``perf_counter`` when something is on.
"""

from __future__ import annotations

import time
from typing import Optional

from . import _state

__all__ = ["span", "spans_active"]

# lazily bound to paddle_tpu.profiler's module-level _active_profilers
# list (a stable object) + its RecordEvent class; the profiler drags jax
# in, so nothing is imported until a span runs with a profiler plausible
_PROF = [None, None]            # [_active_profilers, RecordEvent]


def _profiler_bridge():
    lst = _PROF[0]
    if lst is None:
        try:
            from .. import profiler as _p
            _PROF[0] = lst = _p._active_profilers
            _PROF[1] = _p.RecordEvent
        except Exception:
            _PROF[0] = lst = ()
    return lst


def spans_active() -> bool:
    """True when a span would observe anything (telemetry span hook or
    an active profiler).  Per-call producers (eager collectives) use
    this as a fast path so the fully-disabled cost stays two falsy
    checks, with no span/f-string construction."""
    return _state.SPAN[0] is not None or bool(_profiler_bridge())


class _SpanHook:
    """Installed in ``_state.SPAN[0]`` by ``observability.enable()``:
    routes span begin/ends into the recorder, registry, and sinks."""

    __slots__ = ("_reg", "_emit", "_rec")

    def __init__(self, registry=None, emit=None, recorder=None):
        self._reg = registry
        self._emit = emit
        self._rec = recorder

    def begin(self, name: str) -> None:
        rec = self._rec
        if rec is not None:
            rec.record("span_begin", name=name)

    def end(self, name: str, dur_ms: float, attrs: Optional[dict],
            emit: bool) -> None:
        if emit:
            if self._reg is not None:
                self._reg.histogram(f"span[{name}].ms").observe(dur_ms)
            if self._emit is not None:
                ev = {"event": "span", "name": name,
                      "ms": round(dur_ms, 3)}
                if attrs:
                    ev.update(attrs)
                self._emit(ev)   # lands in the ring via Telemetry.emit
                return
        rec = self._rec
        if rec is not None:
            rec.record("span_end", name=name, ms=round(dur_ms, 3))


class span:
    """Context manager: ``with span("name", **attrs): ...``.

    ``emit=False`` keeps the breadcrumbs and the profiler bridge but
    suppresses the JSONL event + registry histogram — used where another
    event already carries the numbers (TrainStep's ``step`` event).
    """

    __slots__ = ("name", "attrs", "emit", "_t0", "_rec_event", "_hook")

    def __init__(self, name: str, emit: bool = True, **attrs):
        self.name = name
        self.attrs = attrs
        self.emit = emit
        self._rec_event = None
        self._hook = None
        self._t0 = 0.0

    def __enter__(self):
        self._hook = hook = _state.SPAN[0]
        if hook is not None:
            hook.begin(self.name)
        if _profiler_bridge():
            self._rec_event = _PROF[1](self.name)
            self._rec_event.begin()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        rec_event = self._rec_event
        if rec_event is not None:
            rec_event.end()
            self._rec_event = None
        hook = self._hook
        if hook is not None:
            hook.end(self.name, (t1 - self._t0) * 1e3, self.attrs,
                     self.emit)
        return False
