"""Runtime telemetry (reference capability: PaddlePaddle's profiler /
monitor stack, SURVEY §5.5 — always-on runtime statistics, not one-off
benchmarks).

Three pillars:

1. **Metrics registry** (``registry.py``): thread-safe counters, gauges,
   histograms with rolling p50/p95; pluggable sinks (``sinks.py``) —
   in-memory for tests, JSONL file, stdout/stderr — process-0-gated
   under multihost.
2. **StepMonitor** (``step_monitor.py``): ``jit.TrainStep.__call__``,
   ``hapi.Model`` (and through TrainStep, ``distributed.Engine.fit``)
   emit one structured event per step with wall time, tokens/sec and
   MFU, sharing bench.py's flops-per-token math (``mfu.py``) so runtime
   and bench numbers agree by construction.
3. **Recompile sentinel** (``recompile.py``): counts XLA backend
   compiles via ``jax.monitoring``, attributes them to the TrainStep /
   ``to_static`` site, and warns loudly on recompile storms.

Collectives issued through ``paddle_tpu.distributed`` additionally feed
byte/call counters into the registry (``distributed/communication.py``).

Zero overhead when disabled (the default): every producer does ONE falsy
check against a ``_state`` hook container (the ``distributed/debug.py``
pattern) — enforced by the ``telemetry-overhead`` CI gate in
``tools/ci.py``.

Usage::

    import paddle_tpu.observability as obs
    tel = obs.enable(jsonl_path="run_telemetry.jsonl")
    ... train ...
    obs.disable()          # final metrics snapshot + sink close

Event schema: docs/OBSERVABILITY.md.  Report folding:
``python tools/telemetry_report.py run_telemetry.jsonl``.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from . import _state
from .aggregate import (FleetRegistry, HistogramSketch,  # noqa: F401
                        fleet_fold, registry_to_wire,
                        stitch_trace_segments)
from .compiled import (CHIP_SPECS, CompiledArtifactLedger,  # noqa: F401
                       chip_spec, roofline)
from .flight_recorder import (FlightRecorder, install_crash_hooks,  # noqa: F401
                              uninstall_crash_hooks, write_postmortem)
from .flight_recorder import _reset_postmortem, configure_postmortem
from .mfu import (PEAK_BF16_FLOPS, causal_lm_flops_per_token,  # noqa: F401
                  dense_flops_per_token, flops_per_token_of, peak_flops)
from .recompile import (BACKEND_COMPILE_EVENT, RecompileSentinel,  # noqa: F401
                        RecompileStormWarning)
from .registry import Counter, Gauge, Histogram, MetricsRegistry  # noqa: F401
from .sinks import (InMemorySink, JsonlSink, Sink,  # noqa: F401
                    StdoutSink, _ProcessZeroGate)
from .spans import _SpanHook, span  # noqa: F401
from .step_monitor import StepMonitor  # noqa: F401
from .trace import (RequestTrace, RequestTracer, SLOCapture,  # noqa: F401
                    current_trace_id, new_trace_id, trace_context)
from .watchdog import HangWarning, HangWatchdog  # noqa: F401

_ACTIVE: List[Optional["Telemetry"]] = [None]


class Telemetry:
    """One enabled telemetry session: registry + sinks + monitors."""

    def __init__(self, registry: MetricsRegistry, sinks: List[Sink],
                 monitor: Optional[StepMonitor],
                 sentinel: Optional[RecompileSentinel],
                 recorder: Optional[FlightRecorder] = None,
                 watchdog: Optional[HangWatchdog] = None):
        self.registry = registry
        self.sinks = list(sinks)
        self.monitor = monitor
        self.sentinel = sentinel
        self.recorder = recorder
        self.watchdog = watchdog
        self.tracer: Optional[RequestTracer] = None
        self.ledger: Optional[CompiledArtifactLedger] = None
        # RLock, not Lock: the preemption SIGTERM handler emits from the
        # main thread, possibly interrupting an emit already holding the
        # lock — a plain Lock would self-deadlock the dying process
        self._lock = threading.RLock()

    def emit(self, event: dict) -> None:
        """Stamp ``ts`` and fan out to every sink (serialized: events may
        come from the trainer thread and the compile listener at once)."""
        if "ts" not in event:
            event = {"ts": round(time.time(), 3), **event}
        # the flight ring sees every event, BEFORE the sink lock: a sink
        # wedged on a dead filesystem must not starve the post-mortem ring
        rec = self.recorder
        if rec is not None:
            rec.record_event(event)
        with self._lock:
            for s in self.sinks:
                try:
                    s.write(event)
                except Exception:
                    # a broken sink must never take down a train step
                    pass

    def flush(self, emit_metrics: bool = True) -> None:
        """Emit a ``metrics`` registry snapshot, then flush sinks."""
        if emit_metrics:
            self.emit({"event": "metrics",
                       "metrics": self.registry.snapshot()})
        with self._lock:
            for s in self.sinks:
                s.flush()

    def close(self) -> None:
        with self._lock:
            for s in self.sinks:
                s.close()


def enabled() -> bool:
    return _ACTIVE[0] is not None


def get_telemetry() -> Optional[Telemetry]:
    return _ACTIVE[0]


def get_registry() -> Optional[MetricsRegistry]:
    tel = _ACTIVE[0]
    return tel.registry if tel is not None else None


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _state.RECORDER[0]


def get_watchdog() -> Optional[HangWatchdog]:
    tel = _ACTIVE[0]
    return tel.watchdog if tel is not None else None


def get_request_tracer() -> Optional[RequestTracer]:
    """The active request-lifecycle tracer (serving timelines), or None
    when telemetry is disabled / tracing was opted out."""
    return _state.TRACE[0]


def get_ledger() -> Optional[CompiledArtifactLedger]:
    """The active compiled-artifact ledger (per-program cost/memory
    rows + roofline spec), or None when telemetry is disabled / the
    ledger was opted out."""
    return _state.LEDGER[0]


def emit_event(event: str, **fields) -> None:
    """Fire-and-forget structured event; no-op when disabled."""
    emit = _state.EMIT[0]
    if emit is not None:
        emit({"event": event, **fields})


def _record_collective(op: str, axes, arg) -> None:
    """COLLECTIVE hook target: byte/call counters per collective op.

    ``arg`` is the collective's first positional (a tensor, a tensor
    list for the paddle-style all_gather signature, or a P2POp list for
    batch_isend_irecv).  Eager calls count per call; calls inside a jit
    trace count once per trace — per-execution counting would need a
    host callback in the compiled hot path, which is exactly what this
    subsystem promises not to do.
    """
    tel = _ACTIVE[0]
    if tel is None:
        return
    tensors = []
    if hasattr(arg, "shape"):
        tensors = [arg]
    elif isinstance(arg, (list, tuple)):
        for o in arg:
            t = getattr(o, "tensor", o)
            if hasattr(t, "shape"):
                tensors.append(t)
    nbytes = 0
    for t in tensors:
        try:
            n = 1
            for d in t.shape:
                n *= int(d)
            nbytes += n * t.dtype.itemsize
        except Exception:
            pass
    label = ",".join(axes) if axes else "world"
    reg = tel.registry
    reg.counter(f"collective.{op}.calls").inc()
    reg.counter(f"collective.{op}.bytes").inc(nbytes)
    reg.counter(f"collective.{op}[{label}].bytes").inc(nbytes)


def enable(jsonl_path: Optional[str] = None, stdout: bool = False,
           sinks: Optional[List[Sink]] = None, *,
           step_monitor: bool = True, recompile_sentinel: bool = True,
           collectives: bool = True, warmup_steps: int = 1,
           sentinel_warmup: int = 1, storm_threshold: int = 3,
           storm_window_s: float = 60.0, storm_all_sites: bool = False,
           all_processes: bool = False,
           registry: Optional[MetricsRegistry] = None,
           flight_recorder: bool = True,
           flight_recorder_capacity: int = 256,
           spans: bool = True, crash_hooks: bool = True,
           postmortem_path: Optional[str] = None,
           watchdog_s: Optional[float] = None, on_hang=None,
           watchdog_abort: bool = False,
           request_tracing: bool = True,
           trace_capacity: int = 2048,
           compiled_ledger: bool = True,
           chip_spec_override: Optional[dict] = None) -> Telemetry:
    """Turn telemetry on (replacing any active session) and return the
    ``Telemetry`` handle.

    With no sink arguments an ``InMemorySink`` is installed so events are
    at least inspectable via ``get_telemetry().sinks[0]``.  File/stdout
    sinks only write on process 0 unless ``all_processes=True``;
    in-memory sinks are never gated.

    Crash/hang diagnostics (docs/OBSERVABILITY.md): ``flight_recorder``
    keeps the last ``flight_recorder_capacity`` events/breadcrumbs in a
    ring even when sinks are off; ``crash_hooks`` drains it to
    ``postmortem_path`` (default ``<jsonl_path>.postmortem``, else
    ``run.postmortem``) on unhandled exceptions / ``sys.exit`` mid-run /
    SIGQUIT — call ``disable()`` for a clean shutdown without a dump.
    ``watchdog_s`` starts a :class:`HangWatchdog` with that deadline;
    ``on_hang`` (callable) and ``watchdog_abort`` pick the escalation
    beyond the warning+dump.  ``spans`` installs the ``span(...)`` hook
    (per-span events + ``span[<name>].ms`` histograms).

    ``request_tracing`` installs a :class:`RequestTracer` — one
    per-request lifecycle timeline across the serving stack (admission,
    queue wait, prefill chunks, decode, preempt/restore, migration,
    retire; docs/OBSERVABILITY.md "Tracing a request"), retaining the
    last ``trace_capacity`` retired traces for ``GET /v1/requests/<rid>``
    and emitting one ``serve_trace`` event per retired request.

    ``compiled_ledger`` installs a :class:`CompiledArtifactLedger` —
    one row per real backend compile with XLA's cost/memory analysis,
    compile wall-ms, sentinel site attribution, and the analytic
    roofline minimum under the chip spec (``chip_spec_override`` merges
    ``peak_flops``/``hbm_gbps`` on top of the built-in table; see
    docs/OBSERVABILITY.md "Reading the roofline").
    """
    # validate BEFORE any side effect: raising after disable()/sink
    # creation/sentinel install would leak a registered jax.monitoring
    # listener with no _ACTIVE session to tear it down
    if watchdog_s and not flight_recorder:
        raise ValueError(
            "watchdog_s needs the flight recorder: its ring beat is "
            "the liveness signal — drop flight_recorder=False or run "
            "a standalone HangWatchdog with manual beat()s")
    disable()
    out: List[Sink] = list(sinks) if sinks else []
    file_sinks: List[Sink] = []
    if jsonl_path:
        file_sinks.append(JsonlSink(jsonl_path))
    if stdout:
        file_sinks.append(StdoutSink())
    if file_sinks and not all_processes:
        is_zero = True
        try:
            import jax
            is_zero = jax.process_index() == 0
        except Exception:
            pass
        file_sinks = [_ProcessZeroGate(s, is_zero) for s in file_sinks]
    out.extend(file_sinks)
    if not out:
        # bounded: a sinkless enable() (sentinel/registry only) on a
        # long-running job must not grow an event list without limit
        out = [InMemorySink(maxlen=65536)]

    reg = registry if registry is not None else MetricsRegistry()
    rec = FlightRecorder(flight_recorder_capacity) if flight_recorder \
        else None
    tel = Telemetry(reg, out, None, None, recorder=rec)
    sent = None
    if recompile_sentinel:
        sent = RecompileSentinel(tel, reg, warmup=sentinel_warmup,
                                 storm_threshold=storm_threshold,
                                 storm_window_s=storm_window_s,
                                 storm_all_sites=storm_all_sites)
        sent.install()
        tel.sentinel = sent
    if step_monitor:
        tel.monitor = StepMonitor(tel, reg, sentinel=sent,
                                  warmup_steps=warmup_steps)

    pm_path = postmortem_path or (
        jsonl_path + ".postmortem" if jsonl_path else None)
    if rec is not None:
        configure_postmortem(pm_path, recorder=rec,
                             registry_fn=reg.snapshot)
        if crash_hooks:
            install_crash_hooks()
    if watchdog_s:
        tel.watchdog = HangWatchdog(
            deadline_s=watchdog_s, recorder=rec, registry=reg,
            emit=tel.emit, postmortem_path=pm_path, on_hang=on_hang,
            abort=watchdog_abort)

    if request_tracing:
        tel.tracer = RequestTracer(capacity=trace_capacity, registry=reg,
                                   emit=tel.emit)

    if compiled_ledger:
        tel.ledger = CompiledArtifactLedger(
            sentinel=sent, telemetry=tel,
            spec=dict(chip_spec_override) if chip_spec_override else None)
        tel.ledger.install()

    _ACTIVE[0] = tel
    _state.MONITOR[0] = tel.monitor
    _state.EMIT[0] = tel.emit
    _state.TRACE[0] = tel.tracer
    _state.LEDGER[0] = tel.ledger
    _state.COLLECTIVE[0] = _record_collective if collectives else None
    _state.RECORDER[0] = rec
    if spans:
        _state.SPAN[0] = _SpanHook(registry=reg, emit=tel.emit,
                                   recorder=rec)
    if tel.watchdog is not None:
        tel.watchdog.start()
    return tel


def disable() -> None:
    """Tear down: unhook producers, emit a final metrics snapshot, close
    sinks.  Idempotent."""
    tel = _ACTIVE[0]
    if tel is None:
        return
    _state.MONITOR[0] = None
    _state.COLLECTIVE[0] = None
    _state.EMIT[0] = None
    _state.SPAN[0] = None
    _state.RECORDER[0] = None
    _state.TRACE[0] = None
    _state.LEDGER[0] = None
    _ACTIVE[0] = None
    if tel.watchdog is not None:
        tel.watchdog.stop()
    # a clean disable() means the run ended on purpose: no atexit dump
    uninstall_crash_hooks()
    _reset_postmortem()
    if tel.sentinel is not None:
        tel.sentinel.uninstall()
    if tel.ledger is not None:
        tel.ledger.uninstall()
    try:
        tel.flush(emit_metrics=True)
    finally:
        tel.close()


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
