"""Hang watchdog: a daemon thread that turns "the job went quiet" into
an on-disk post-mortem within a bounded deadline.

The liveness signal is the flight recorder's ``last_beat`` — every step
``span_begin``, collective issue, ckpt span, compile event, and emitted
telemetry event stamps it — plus an explicit ``beat()`` for loops that
produce no telemetry (data loading, setup).  Crucially the beat fires at
operation BEGIN (the ``span_begin`` breadcrumb), so a step or collective
that enters and never returns shows a growing age, not a frozen clock.

When no beat lands within ``deadline_s``, the watchdog — from its own
thread, which is exactly why it can observe a wedged main thread —
writes a post-mortem (all thread stacks, the ring, a registry snapshot),
then escalates: ``HangWarning`` always, then the ``on_hang`` callback if
given, then ``os._exit`` if ``abort=True`` (a multihost job wedged on
one host should die loudly so the launcher's elastic restart can act,
rather than burn the whole slice forever).  One dump per stall episode:
it re-arms only after progress resumes.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
import warnings
from typing import Callable, Optional

from .flight_recorder import FlightRecorder, write_postmortem

__all__ = ["HangWatchdog", "HangWarning"]


class HangWarning(RuntimeWarning):
    """No step/collective/span progress within the watchdog deadline."""


class HangWatchdog:
    """Daemon-thread stall detector over the flight recorder's beat.

    Usage (``observability.enable(watchdog_s=300)`` does this wiring)::

        wd = HangWatchdog(deadline_s=300, recorder=rec,
                          postmortem_path="run.jsonl.postmortem")
        wd.start()
        ... train ...
        wd.stop()

    Pick ``deadline_s`` above the worst first-step XLA compile: no beat
    lands while the compiler runs, so a long compile reads as a stall —
    the dump disambiguates (main thread inside ``backend_compile`` =
    still compiling; see docs/OBSERVABILITY.md).
    """

    def __init__(self, deadline_s: float = 300.0,
                 poll_s: Optional[float] = None,
                 recorder: Optional[FlightRecorder] = None,
                 registry=None, emit=None,
                 postmortem_path: Optional[str] = None,
                 on_hang: Optional[Callable[["HangWatchdog"], None]] = None,
                 abort: bool = False):
        self.deadline_s = float(deadline_s)
        # poll often enough that a fire lands "within its deadline" plus
        # a fraction, without busy-waiting on long deadlines
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(min(self.deadline_s / 4.0, 10.0), 0.05)
        self._recorder = recorder
        self._registry = registry
        self._emit = emit
        self._postmortem_path = postmortem_path
        self.on_hang = on_hang
        self.abort = bool(abort)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._manual_beat = time.monotonic()
        self._stalled = False
        self._fire_beat = 0.0
        self.fired = 0
        self.last_dump: Optional[str] = None

    # -- liveness ----------------------------------------------------------

    def beat(self) -> None:
        """Manual liveness beat for phases that emit no telemetry."""
        self._manual_beat = time.monotonic()

    def _last_beat(self) -> float:
        b = self._manual_beat
        rec = self._recorder
        if rec is not None and rec.last_beat > b:
            b = rec.last_beat
        return b

    def age_s(self) -> float:
        return time.monotonic() - self._last_beat()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "HangWatchdog":
        if self._thread is not None:
            return self
        self.beat()          # arm from start(), not construction
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="pdtpu-hang-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.poll_s + 1.0)
            self._thread = None

    # -- the loop ----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self._stalled:
                # one dump per stall episode: re-arm only on a beat NEWER
                # than the fire's own "hang" emission (which lands in the
                # ring and must not read as progress)
                if self._last_beat() > self._fire_beat:
                    self._stalled = False
                continue
            age = self.age_s()
            if age <= self.deadline_s:
                continue
            self._stalled = True
            self._fire(age)
            self._fire_beat = self._last_beat()

    def _fire(self, age: float) -> None:
        self.fired += 1
        reason = (f"hang: no step/collective/span progress for "
                  f"{age:.1f}s (deadline {self.deadline_s:.1f}s)")
        # post-mortem FIRST and via a direct file write: the emit path
        # can block on a lock the wedged thread is holding
        self.last_dump = write_postmortem(
            reason=reason, path=self._postmortem_path,
            recorder=self._recorder,
            registry_fn=(self._registry.snapshot
                         if self._registry is not None else None))
        try:
            # guarded like every other escalation step: under -W error
            # the raise would otherwise kill the watchdog thread and
            # silently end stall detection for the rest of the run
            warnings.warn(
                f"hang watchdog: {reason}. Thread stacks + the last "
                f"{self._recorder.capacity if self._recorder else 0} "
                f"flight-recorder events are in {self.last_dump!r} — see "
                "docs/OBSERVABILITY.md (\"Reading a hang dump\").",
                HangWarning, stacklevel=2)
        except Exception:
            pass
        cb = self.on_hang
        if callable(cb):
            try:
                cb(self)
            except Exception:
                pass
        if self._emit is not None:
            # emit LAST and on a helper thread with a bounded join: a
            # wedged trainer may hold the sink lock, and a blocked emit
            # here must not stop the abort below (or future stall
            # episodes).  The join normally completes — emit beats the
            # ring before touching the sink lock — so the loop's
            # _fire_beat capture sees this beat and does not read it as
            # progress.
            ev = {"event": "hang", "age_s": round(age, 1),
                  "deadline_s": self.deadline_s,
                  "postmortem": self.last_dump}

            def _bg_emit():
                try:
                    self._emit(ev)
                except Exception:
                    pass

            try:
                t = threading.Thread(target=_bg_emit, daemon=True)
                t.start()
                t.join(timeout=1.0)
            except Exception:
                pass
        if self.abort:
            # last resort: raw stacks to stderr (async-signal-safe),
            # then hard-exit so the launcher's elastic restart can act
            try:
                faulthandler.dump_traceback(file=sys.stderr)
            except Exception:
                pass
            os._exit(42)
