"""Recompile sentinel: count XLA compilations, attribute them, catch storms.

The classic TPU production failure is shape churn: a dynamic batch/seq
dimension (or a Python scalar leaking into a traced signature) makes
``jax.jit`` specialize per shape, and a job that benchmarked at 0.5 MFU
spends its life in the compiler — silently, because nothing in the
runtime counts compilations.  The reference framework surfaces this
through its profiler/monitor stack; jax exposes the raw signal via
``jax.monitoring`` (pinned 0.4.37: ``/jax/core/compile/
backend_compile_duration`` fires once per real backend compile, cache
hits excluded).

This module turns that signal into:

- per-site compile counters + duration histograms in the registry
  (site = the TrainStep / to_static callable that triggered tracing,
  threaded through a thread-local set by ``StepMonitor``);
- one ``compile`` JSONL event per compilation;
- a loud ``RecompileStormWarning`` + ``recompile_storm`` event when a
  site keeps compiling after its warmup allowance — >N compiles beyond
  warmup inside a rolling window.
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from typing import Optional

__all__ = ["RecompileSentinel", "RecompileStormWarning",
           "BACKEND_COMPILE_EVENT"]

# jax 0.4.37: jax._src.dispatch.BACKEND_COMPILE_EVENT — the string is
# stable monitoring API surface; not imported from the private module.
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

UNATTRIBUTED = "<unattributed>"


class RecompileStormWarning(RuntimeWarning):
    """A jit site kept recompiling after warmup — shape churn on TPU."""


class RecompileSentinel:
    """Listener on ``jax.monitoring`` compile-duration events.

    ``warmup`` compilations per site are expected (the initial trace, an
    accumulate-flag variant); each compile beyond that counts toward the
    storm window.  ``storm_threshold`` post-warmup compiles for one site
    within ``storm_window_s`` seconds trigger the warning, re-armed at
    most once per window per site so a pathological job warns every
    window, not every step.

    Unattributed compiles (eager ops, setup-phase jits outside any
    TrainStep/to_static call) are counted and emitted but excluded from
    storm WARNINGS by default — a normal startup does dozens of small
    one-off compiles that share the ``<unattributed>`` bucket and would
    trip any useful threshold.  ``storm_all_sites=True`` re-includes
    them.
    """

    def __init__(self, telemetry=None, registry=None, *, warmup: int = 1,
                 storm_threshold: int = 3, storm_window_s: float = 60.0,
                 storm_all_sites: bool = False):
        self._tel = telemetry
        self._reg = registry
        self.warmup = int(warmup)
        self.storm_threshold = int(storm_threshold)
        self.storm_window_s = float(storm_window_s)
        self.storm_all_sites = bool(storm_all_sites)
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._installed = False
        self._active = False
        self.total_compiles = 0
        self._per_site: dict = {}        # site -> compile count
        self._post_warmup: dict = {}     # site -> deque[t] inside window
        self._last_warn: dict = {}       # site -> t of last storm warning

    # -- site attribution --------------------------------------------------

    class _SiteScope:
        __slots__ = ("_sent", "_name", "_warmup")

        def __init__(self, sent, name, warmup):
            self._sent = sent
            self._name = name
            self._warmup = warmup

        def __enter__(self):
            stack = getattr(self._sent._tls, "stack", None)
            if stack is None:
                stack = self._sent._tls.stack = []
            stack.append((self._name, self._warmup))
            return self

        def __exit__(self, *exc):
            self._sent._tls.stack.pop()
            return False

    def site(self, name: str, *, warmup: bool = False) -> "_SiteScope":
        """Context manager: compiles fired inside are attributed to
        ``name`` (a TrainStep/to_static call site).  ``warmup=True``
        marks the compiles as EXPECTED — they count and attribute like
        any other but never enter the storm window, so a process that
        legitimately warms the same site repeatedly (bench scenarios,
        one engine per test, a re-built engine after evacuation) stays
        quiet while genuine shape churn outside a warmup scope still
        warns."""
        return self._SiteScope(self, name, warmup)

    def current_site(self) -> str:
        stack = getattr(self._tls, "stack", None)
        return stack[-1][0] if stack else UNATTRIBUTED

    def _current_scope(self):
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else (UNATTRIBUTED, False)

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> None:
        if not self._installed:
            import jax
            jax.monitoring.register_event_duration_secs_listener(self._on_event)
            self._installed = True
        self._active = True

    def uninstall(self) -> None:
        """Deactivate; physically unregister when jax exposes the hook.

        0.4.37 only has the private test helper, so the fallback is a
        registered-but-inert listener (``_active`` gates everything)."""
        self._active = False
        if not self._installed:
            return
        try:
            from jax._src import monitoring as _m
            _m._unregister_event_duration_listener_by_callback(self._on_event)
            self._installed = False
        except Exception:
            pass

    # -- the listener ------------------------------------------------------

    def _on_event(self, event: str, duration_secs: float, **kw) -> None:
        if not self._active or event != BACKEND_COMPILE_EVENT:
            return
        site, expected = self._current_scope()
        now = time.monotonic()
        storm = None
        with self._lock:
            self.total_compiles += 1
            n = self._per_site.get(site, 0) + 1
            self._per_site[site] = n
            if n > self.warmup and not expected \
                    and (site != UNATTRIBUTED or self.storm_all_sites):
                window = self._post_warmup.setdefault(site, deque())
                window.append(now)
                while window and now - window[0] > self.storm_window_s:
                    window.popleft()
                if (len(window) >= self.storm_threshold
                        and now - self._last_warn.get(site, -1e30)
                        >= self.storm_window_s):
                    self._last_warn[site] = now
                    storm = len(window)
            total = self.total_compiles
        if self._reg is not None:
            self._reg.counter("compile.count").inc()
            self._reg.counter(f"compile[{site}].count").inc()
            self._reg.histogram("compile.duration_ms").observe(
                duration_secs * 1e3)
            # scrapeable per-site attribution: the bracket=pair grammar
            # renders as recompiles_total{site="..."} on /metrics (both
            # the engine surface and the cluster fleet fold), where the
            # compile[<site>].count spelling above becomes a label on
            # the *compile_count* family keyed by the dotted head.  The
            # reserved grammar chars ("[],=") are squashed exactly like
            # aggregate._label_value so wire snapshots round-trip.
            site_l = site
            for ch in "[],=":
                site_l = site_l.replace(ch, "_")
            self._reg.counter(f"recompiles_total[site={site_l}]").inc()
        if self._tel is not None:
            self._tel.emit({"event": "compile", "site": site,
                            "duration_ms": round(duration_secs * 1e3, 3),
                            "site_count": n, "count": total})
        if storm is not None:
            msg = (f"recompile storm: {site} compiled {storm} times beyond "
                   f"its {self.warmup}-compile warmup within "
                   f"{self.storm_window_s:.0f}s — a traced shape or static "
                   "arg is churning (dynamic batch/seq dim, Python scalar "
                   "in the signature). Every compile stalls the whole "
                   "slice; pad shapes to fixed buckets or hoist the "
                   "changing value out of the traced signature. See "
                   "docs/OBSERVABILITY.md.")
            if self._tel is not None:
                self._tel.emit({"event": "recompile_storm", "site": site,
                                "compiles_after_warmup": storm,
                                "window_s": self.storm_window_s,
                                "site_count": n})
            warnings.warn(msg, RecompileStormWarning, stacklevel=2)

    # -- introspection -----------------------------------------------------

    def compiles(self, site: Optional[str] = None) -> int:
        if site is None:
            return self.total_compiles
        return self._per_site.get(site, 0)
