"""Compiled-artifact ledger: what each XLA program costs to build and run.

The stack measures wall time everywhere (step events, serve.step_ms,
span histograms) but never confronts it with what the compiled program
*should* cost.  XLA already knows: every ``MeshExecutable`` carries
``cost_analysis()`` (flops, bytes accessed) and ``memory_analysis()``
(argument/output/temp bytes) — this module captures both, once per real
backend compile, into per-program rows keyed to the recompile
sentinel's site attribution.  On top of the rows:

- an **analytic roofline**: a small overridable chip-spec table (peak
  FLOP/s + HBM GB/s; CPU gets a measured stand-in) turns each program's
  flops/bytes into a compute-bound or bandwidth-bound minimum step
  time, so ``serve.roofline.*`` / ``train.roofline.*`` gauges can say
  how close measured wall time sits to the hardware limit;
- **HBM accounting inputs**: per-program ``temp``/``argument``/
  ``output`` bytes feed the ``serve.hbm.*`` gauges next to the actual
  pool buffer sizes.

Capture point: ``jax._src.interpreters.pxla.MeshComputation.compile``
— the one choke point both normal jit dispatch and AOT lowering flow
through in the pinned jax (0.4.37).  Wrapping it sees exactly one
executable per real backend compile (cache hits never reach it), so the
ledger adds ZERO compiles and changes no behavior; the wrapper is only
installed while telemetry is enabled (``observability.enable()``), so
the disabled cost is literally nothing.

Like ``aggregate.py``/``sinks.py`` this module loads standalone (no
package import, no relative imports, jax optional) so offline tools can
reuse the chip-spec table and roofline math.  The FLOP/s column must
stay consistent with ``mfu.PEAK_BF16_FLOPS`` — a unit test pins them
together.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

__all__ = ["CompiledArtifactLedger", "CHIP_SPECS", "chip_spec",
           "roofline"]

UNATTRIBUTED = "<unattributed>"     # mirrors recompile.UNATTRIBUTED

# Per-chip peak bf16 FLOP/s and HBM bandwidth (GB/s).  FLOP/s numbers
# are THE same values as observability/mfu.py's PEAK_BF16_FLOPS (pinned
# by tests/test_compiled_obs.py); bandwidths are the published per-chip
# HBM numbers.  Keys are device_kind prefixes, longest match wins.
CHIP_SPECS = {
    "TPU v5 lite": {"peak_flops": 197e12, "hbm_gbps": 819.0},   # v5e
    "TPU v5e": {"peak_flops": 197e12, "hbm_gbps": 819.0},
    "TPU v5p": {"peak_flops": 459e12, "hbm_gbps": 2765.0},
    "TPU v5": {"peak_flops": 459e12, "hbm_gbps": 2765.0},
    "TPU v4": {"peak_flops": 275e12, "hbm_gbps": 1228.0},
    "TPU v6 lite": {"peak_flops": 918e12, "hbm_gbps": 1640.0},  # v6e
    # CPU: nominal flops (CI only, matches mfu.py); bandwidth is a
    # measured stand-in (see _measured_cpu_gbps) so CPU rooflines are
    # at least the right order of magnitude rather than pure fiction.
    "cpu": {"peak_flops": 1e12, "hbm_gbps": None},
}

_CPU_GBPS = [None]  # measured once per process


def _measured_cpu_gbps() -> float:
    """Measured CPU memory bandwidth stand-in: time a few large
    bytearray copies (stdlib-only).  Cached per process; clamped to a
    sane floor so a loaded CI machine can't produce absurd rooflines."""
    if _CPU_GBPS[0] is not None:
        return _CPU_GBPS[0]
    n = 32 * 1024 * 1024                       # 32 MiB, past L2
    src = bytearray(n)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        dst = bytes(src)                       # one read + one write
        dt = time.perf_counter() - t0
        best = min(best, dt)
        del dst
    gbps = (2.0 * n / best) / 1e9 if best > 0 else 10.0
    _CPU_GBPS[0] = max(1.0, min(gbps, 1000.0))
    return _CPU_GBPS[0]


def chip_spec(kind: Optional[str] = None, override: Optional[dict] = None
              ) -> dict:
    """Resolve the roofline spec for a device kind.

    ``kind=None`` asks jax for device 0's ``device_kind`` (falling back
    to ``"cpu"`` when jax is absent — the standalone-load contract).
    ``override`` merges user-supplied ``peak_flops``/``hbm_gbps`` on
    top, the escape hatch for chips not in the table.
    Returns ``{"kind", "peak_flops", "hbm_gbps"}``.
    """
    if kind is None:
        kind = "cpu"
        try:
            import jax
            kind = getattr(jax.devices()[0], "device_kind", "cpu")
        except Exception:
            pass
    spec = None
    best_len = -1
    for k, v in CHIP_SPECS.items():
        if kind.startswith(k) and len(k) > best_len:
            spec, best_len = v, len(k)
    if spec is None:
        spec = CHIP_SPECS["cpu"]
    out = {"kind": kind, "peak_flops": spec["peak_flops"],
           "hbm_gbps": spec["hbm_gbps"]}
    if out["hbm_gbps"] is None:
        out["hbm_gbps"] = _measured_cpu_gbps()
    if override:
        out.update({k: v for k, v in override.items() if v is not None})
    return out


def roofline(flops: float, bytes_accessed: float, spec: dict) -> dict:
    """Analytic minimum execution time for one program under ``spec``.

    ``t_compute = flops / peak_flops``, ``t_memory = bytes /
    (hbm_gbps * 1e9)``; the program cannot finish faster than the
    larger of the two.  Returns ``{"min_ms", "compute_ms", "memory_ms",
    "bound"}`` where ``bound`` is ``"compute"`` or ``"bandwidth"``
    (ties go to compute — the flattering read for a matmul-heavy
    program sitting exactly on the ridge).
    """
    peak = float(spec.get("peak_flops") or 1e12)
    gbps = float(spec.get("hbm_gbps") or 1.0)
    t_c = float(flops) / peak
    t_m = float(bytes_accessed) / (gbps * 1e9)
    bound = "compute" if t_c >= t_m else "bandwidth"
    return {"min_ms": max(t_c, t_m) * 1e3, "compute_ms": t_c * 1e3,
            "memory_ms": t_m * 1e3, "bound": bound}


class CompiledArtifactLedger:
    """Per-compile cost/memory rows with site attribution.

    ``install()`` wraps ``pxla.MeshComputation.compile`` (jax-optional:
    a no-op when jax is absent); every real backend compile then lands
    one row via :meth:`record_executable`.  ``uninstall()`` restores
    the original method — ``observability.disable()`` calls it, so the
    wrapper never outlives the telemetry session.
    """

    def __init__(self, sentinel=None, telemetry=None,
                 spec: Optional[dict] = None):
        self._sentinel = sentinel
        self._tel = telemetry
        self._spec = spec               # resolved lazily on first row
        self._rows: List[dict] = []
        self._hbm: dict = {}
        self._lock = threading.Lock()
        self._installed = False
        self._orig_compile = None

    # -- chip spec ---------------------------------------------------------

    @property
    def spec(self) -> dict:
        if self._spec is None or "peak_flops" not in self._spec:
            self._spec = chip_spec(override=self._spec)
        return self._spec

    # -- lifecycle ---------------------------------------------------------

    def install(self) -> None:
        """Wrap the one compile choke point.  Idempotent; silently a
        no-op without jax (standalone contract)."""
        if self._installed:
            return
        try:
            from jax._src.interpreters import pxla
        except Exception:
            return
        orig = pxla.MeshComputation.compile
        ledger = self

        def _ledger_compile(comp, *args, **kw):
            t0 = time.perf_counter()
            executable = orig(comp, *args, **kw)
            try:
                ledger.record_executable(
                    executable,
                    program=str(getattr(comp, "_name", "") or "<unnamed>"),
                    compile_ms=(time.perf_counter() - t0) * 1e3)
            except Exception:
                # accounting must never break a compile
                pass
            return executable

        self._orig_compile = orig
        pxla.MeshComputation.compile = _ledger_compile
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        try:
            from jax._src.interpreters import pxla
            if self._orig_compile is not None:
                pxla.MeshComputation.compile = self._orig_compile
        except Exception:
            pass
        self._installed = False
        self._orig_compile = None

    # -- capture -----------------------------------------------------------

    def record_executable(self, executable, *, program: str = "<unnamed>",
                          compile_ms: float = 0.0) -> dict:
        """Extract one row from a compiled executable (duck-typed:
        ``cost_analysis()`` / ``memory_analysis()`` both optional, so a
        backend without them still yields the compile-ms row)."""
        site = UNATTRIBUTED
        if self._sentinel is not None:
            try:
                site = self._sentinel.current_site()
            except Exception:
                pass
        row = {"site": site, "program": program,
               "compile_ms": round(float(compile_ms), 3),
               "flops": 0.0, "bytes_accessed": 0.0,
               "argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0,
               "alias_bytes": 0, "generated_code_bytes": 0,
               "peak_bytes": 0}
        try:
            ca = executable.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            row["flops"] = float(ca.get("flops", 0.0) or 0.0)
            row["bytes_accessed"] = float(
                ca.get("bytes accessed", 0.0) or 0.0)
        except Exception:
            pass
        try:
            ma = executable.memory_analysis()
            for attr, key in (
                    ("argument_size_in_bytes", "argument_bytes"),
                    ("output_size_in_bytes", "output_bytes"),
                    ("temp_size_in_bytes", "temp_bytes"),
                    ("alias_size_in_bytes", "alias_bytes"),
                    ("generated_code_size_in_bytes",
                     "generated_code_bytes")):
                row[key] = int(getattr(ma, attr, 0) or 0)
            # live-at-peak estimate: everything resident while the
            # program runs, minus donated/aliased input bytes counted
            # twice on the argument AND output side
            row["peak_bytes"] = max(0, row["argument_bytes"]
                                    + row["output_bytes"]
                                    + row["temp_bytes"]
                                    + row["generated_code_bytes"]
                                    - row["alias_bytes"])
        except Exception:
            pass
        rl = roofline(row["flops"], row["bytes_accessed"], self.spec)
        row["min_ms"] = round(rl["min_ms"], 6)
        row["bound"] = rl["bound"]
        with self._lock:
            self._rows.append(row)
        tel = self._tel
        if tel is not None:
            try:
                tel.emit({"event": "compiled_artifact", **row})
            except Exception:
                pass
        return row

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> List[dict]:
        """Copy of all rows (dicts are shallow-copied: callers mutate
        freely, e.g. the postmortem writer)."""
        with self._lock:
            return [dict(r) for r in self._rows]

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def rows_for(self, site: str) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._rows if r["site"] == site]

    def min_ms_for(self, site: str) -> Optional[float]:
        """Roofline minimum step time for ``site``'s dominant program
        (the row with the largest analytic minimum — a site that
        compiled variants runs ONE of them per step, and the dominant
        one is the steady-state step).  None if the site never
        compiled or its programs carried no cost analysis."""
        best = None
        with self._lock:
            for r in self._rows:
                if r["site"] == site and r["min_ms"] > 0:
                    if best is None or r["min_ms"] > best:
                        best = r["min_ms"]
        return best

    # -- HBM gauge snapshot (for exit reports / postmortems) ---------------

    def set_hbm(self, stats: dict) -> None:
        """Attach the latest ``{pool: bytes}`` HBM snapshot (engine
        warmup publishes it) so postmortems and exit reports carry the
        memory picture without re-touching device buffers."""
        with self._lock:
            self._hbm = dict(stats)

    @property
    def hbm(self) -> dict:
        with self._lock:
            return dict(self._hbm)
