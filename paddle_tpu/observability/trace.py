"""Request-lifecycle tracing: one host-side timeline per serving request.

The serving metrics (``serve.ttft_ms``, ``serve.step_ms``) can say *that*
latency degraded; this module says *where a given request spent its
time* — FrontDoor admission → queue wait → each chunked-prefill span →
decode → preempt/swap/restore → replica migration → retire.  One
:class:`RequestTrace` per request, produced by a process-global
:class:`RequestTracer` installed in ``_state.TRACE[0]`` by
``observability.enable()`` (one falsy check per site when disabled — the
same zero-overhead contract as every other producer, enforced by the
``telemetry-overhead`` CI gate).

Identity and propagation:

- A **trace id** names the request across process boundaries.  It comes
  from (in order) an explicit ``trace_id=``, the ``current_trace_id``
  contextvar (set via :func:`trace_context` — the HTTP server sets it
  from an ``X-Trace-Id`` header), or a generated ``tr-<pid>-<n>``.
- The tracer is keyed by **request id**, and the id rides the
  ``Request`` object itself (``Request.trace_id``), so the trace
  survives preempt→swap→restore and replica-failure evacuation — the
  migrated state keeps feeding the same timeline.

Phase accounting is exact by construction: a trace is always in exactly
one of the phases ``queue`` / ``prefill`` / ``xfer`` / ``decode``; every
transition closes the current segment at the same clock read that opens
the next, so ``queue_ms + prefill_ms + xfer_ms + decode_ms == wall_ms``
to float precision.  The ``xfer`` phase is the disaggregated-serving
handoff window (docs/SERVING.md "Disaggregated serving"): a prefill
replica enters it at first token when the request will stream its KV
pages to a decode replica, and the transfer transition back to ``queue``
closes it — colocated serving never enters it, so its accumulator stays
zero.  Transitions observe the phase histograms ``serve.queue_ms`` (per
queue-wait episode), ``serve.prefill_ms`` (once, at first token),
``serve.xfer_ms`` (per handoff episode) and
``serve.decode_ms_per_token`` (at retire), plus their
``serve.tenant[<t>].*`` per-tenant aggregates.

Consumption: ``GET /v1/requests/<rid>`` on the serving server returns
:meth:`RequestTracer.timeline`; every retired trace is also emitted as
one ``serve_trace`` JSONL event, which ``tools/trace_export.py`` folds
into Perfetto-loadable Chrome trace-event JSON and
``tools/telemetry_report.py`` folds into per-phase/per-tenant tables.

:class:`SLOCapture` closes the loop from signal to evidence: when TTFT
p95 breaches a threshold for K consecutive windows, it arms a bounded
``jax.profiler`` capture (via ``profiler.windowed_profiler``) of the
next N engine steps and emits a ``serve_slo_capture`` event naming the
trace directory.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import os
import threading
import time
from typing import Dict, List, Optional

from . import _state

__all__ = ["RequestTrace", "RequestTracer", "SLOCapture", "current_trace_id",
           "new_trace_id", "trace_context"]

_PHASES = ("queue", "prefill", "xfer", "decode")
_ids = itertools.count()

# the cross-boundary propagation channel: a caller (HTTP handler, test,
# batch driver) sets this around submit and every request created inside
# inherits the id — contextvars so concurrent handler threads never
# bleed ids into each other's submissions
current_trace_id: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("pdtpu_trace_id", default=None)


def new_trace_id() -> str:
    """Process-unique trace id (``tr-<pid>-<n>``)."""
    return f"tr-{os.getpid():x}-{next(_ids):x}"


@contextlib.contextmanager
def trace_context(trace_id: Optional[str] = None):
    """Bind ``trace_id`` (generated when None) as the current trace id
    for submissions made inside the scope; yields the id."""
    tid = trace_id or new_trace_id()
    tok = current_trace_id.set(tid)
    try:
        yield tid
    finally:
        current_trace_id.reset(tok)


class RequestTrace:
    """One request's timeline: an ordered, bounded event list plus
    exact per-phase accumulators (see the module docstring)."""

    __slots__ = ("trace_id", "request_id", "tenant", "t0", "_p0",
                 "events", "phase", "_phase_t", "queue_ms", "prefill_ms",
                 "xfer_ms", "decode_ms", "decode_tokens",
                 "prefill_chunks", "preempts", "handoffs", "done",
                 "finish_reason", "dropped", "_prefill_obs",
                 "max_events")

    def __init__(self, trace_id: str, request_id: str,
                 tenant: Optional[str], p_now: float,
                 max_events: int = 256):
        self.trace_id = trace_id
        self.request_id = request_id
        self.tenant = tenant
        self.t0 = time.time()        # wall anchor for exported traces
        self._p0 = p_now             # perf_counter anchor for offsets
        self.events: List[dict] = []
        self.phase = "queue"
        self._phase_t = p_now
        self.queue_ms = 0.0
        self.prefill_ms = 0.0
        self.xfer_ms = 0.0
        self.decode_ms = 0.0
        self.decode_tokens = 0
        self.prefill_chunks = 0
        self.preempts = 0
        self.handoffs = 0            # prefill→decode replica transfers
        self.done = False
        self.finish_reason: Optional[str] = None
        self.dropped = 0             # events beyond max_events
        self._prefill_obs = False    # serve.prefill_ms observed once
        self.max_events = max_events

    def add(self, phase: str, p_now: float, force: bool = False,
            **attrs) -> None:
        """Append one timeline event (bounded: beyond ``max_events``
        only forced events — retire — land, others count ``dropped``)."""
        if len(self.events) >= self.max_events and not force:
            self.dropped += 1
            return
        ev = {"phase": phase,
              "t_ms": round((p_now - self._p0) * 1e3, 3)}
        ev.update(attrs)
        self.events.append(ev)

    def to_phase(self, phase: Optional[str], p_now: float):
        """Close the current phase segment at ``p_now`` and enter
        ``phase`` (None = final close).  Returns ``(closed_phase,
        segment_ms)`` — contiguous segments are what make the
        accumulators sum exactly to wall time."""
        seg_ms = (p_now - self._phase_t) * 1e3
        closed = self.phase
        if closed == "queue":
            self.queue_ms += seg_ms
        elif closed == "prefill":
            self.prefill_ms += seg_ms
        elif closed == "xfer":
            self.xfer_ms += seg_ms
        elif closed == "decode":
            self.decode_ms += seg_ms
        self.phase = phase
        self._phase_t = p_now
        return closed, seg_ms

    @property
    def wall_ms(self) -> float:
        return self.queue_ms + self.prefill_ms + self.decode_ms

    def summary(self) -> dict:
        q = round(self.queue_ms, 3)
        p = round(self.prefill_ms, 3)
        x = round(self.xfer_ms, 3)
        d = round(self.decode_ms, 3)
        # wall from the ROUNDED parts: the reported invariant
        # queue + prefill + xfer + decode == wall holds exactly as
        # printed (xfer is 0.0 outside disaggregated serving, so the
        # colocated three-phase sum is unchanged)
        return {"queue_ms": q,
                "prefill_ms": p,
                "xfer_ms": x,
                "decode_ms": d,
                "wall_ms": round(q + p + x + d, 3),
                "decode_tokens": self.decode_tokens,
                "prefill_chunks": self.prefill_chunks,
                "preempts": self.preempts,
                "handoffs": self.handoffs,
                "done": self.done,
                "reason": self.finish_reason,
                "dropped_events": self.dropped}

    def timeline(self) -> dict:
        return {"trace_id": self.trace_id,
                "request_id": self.request_id,
                "tenant": self.tenant,
                "t0": round(self.t0, 3),
                "events": [dict(e) for e in self.events],
                "summary": self.summary()}


class RequestTracer:
    """The process-global trace store + producer surface
    (``_state.TRACE[0]`` while observability is enabled).

    All methods are no-ops for unknown request ids (tracing may be
    enabled mid-flight) and safe under the serving stack's threading
    model: one internal lock serializes handler-thread ``begin`` against
    loop-thread phase updates.  Retention is bounded: ``capacity``
    retired traces stay queryable (``GET /v1/requests/<rid>``), older
    ones are evicted — live traces are bounded by the engines' own
    queue+slot+retention bookkeeping.
    """

    def __init__(self, capacity: int = 2048, registry=None, emit=None,
                 clock=time.perf_counter, max_events: int = 256):
        self.capacity = int(capacity)
        self.max_events = int(max_events)
        self._reg = registry
        self._emit = emit
        self._clock = clock
        self._lock = threading.Lock()
        self._traces: Dict[str, RequestTrace] = {}
        self._finished: "collections.deque[str]" = collections.deque()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    # -- producer surface --------------------------------------------------

    def begin(self, request_id: str, *, tenant: Optional[str] = None,
              trace_id: Optional[str] = None, **attrs) -> str:
        """Get-or-create the trace for ``request_id`` and return its
        trace id; the create path records the ``submit`` event.  A
        door-submitted request reaching ``Engine.add_request`` hits the
        get path, so ``submit`` appears exactly once."""
        with self._lock:
            t = self._traces.get(request_id)
            if t is not None and not t.done:
                return t.trace_id
            if t is not None:
                # a legitimately REUSED request id (the engine's
                # keep_finished window is smaller than trace_capacity):
                # the retired timeline must not absorb the new request's
                # events — start fresh, and drop the old id from the
                # retention queue so eviction can't reap the new trace
                # in its place
                try:
                    self._finished.remove(request_id)
                except ValueError:
                    pass
            tid = trace_id or current_trace_id.get() or new_trace_id()
            t = RequestTrace(tid, request_id, tenant, self._clock(),
                             max_events=self.max_events)
            t.add("submit", t._p0, **attrs)
            self._traces[request_id] = t
            return tid

    def point(self, request_id: str, name: str, **attrs) -> None:
        """Record an instantaneous event (no phase change): prefill
        chunks, restore, route, migrate, isolated..."""
        with self._lock:
            t = self._traces.get(request_id)
            if t is None or t.done:
                return
            if name == "prefill_chunk":
                t.prefill_chunks += 1
            t.add(name, self._clock(), **attrs)

    def transition(self, request_id: str, phase: str,
                   event: Optional[str] = None, **attrs) -> None:
        """Move the request into ``phase`` (queue/prefill/decode),
        closing the current segment; records an event carrying the
        closed phase + its duration, and feeds the phase histograms."""
        with self._lock:
            t = self._traces.get(request_id)
            if t is None or t.done:
                return
            now = self._clock()
            closed, seg_ms = t.to_phase(phase, now)
            if event == "preempt":
                t.preempts += 1
            if phase == "xfer":
                t.handoffs += 1
            t.add(event or phase, now, closed=closed,
                  ms=round(seg_ms, 3), **attrs)
            reg = self._reg
            if reg is None:
                return
            if closed == "queue":
                # one observation per queue-wait EPISODE (submit→admit,
                # and each preempt→re-admit wait)
                reg.histogram("serve.queue_ms").observe(seg_ms)
                if t.tenant:
                    reg.histogram(
                        f"serve.tenant[{t.tenant}].queue_ms").observe(
                            seg_ms)
            if closed == "xfer":
                # one observation per handoff EPISODE (first token →
                # pages landed on the decode replica's queue)
                reg.histogram("serve.xfer_ms").observe(seg_ms)
                if t.tenant:
                    reg.histogram(
                        f"serve.tenant[{t.tenant}].xfer_ms").observe(
                            seg_ms)
            if phase == "decode" and closed == "prefill" \
                    and not t._prefill_obs:
                t._prefill_obs = True
                reg.histogram("serve.prefill_ms").observe(t.prefill_ms)
                if t.tenant:
                    reg.histogram(
                        f"serve.tenant[{t.tenant}].prefill_ms").observe(
                            t.prefill_ms)

    def retire(self, request_id: str, *, reason: Optional[str] = None,
               tokens: int = 0, **attrs) -> None:
        """Close the trace: final segment, ``retire`` event,
        ``serve.decode_ms_per_token`` observation, retention eviction,
        and ONE ``serve_trace`` event with the full timeline."""
        with self._lock:
            t = self._traces.get(request_id)
            if t is None or t.done:
                return
            now = self._clock()
            closed, seg_ms = t.to_phase(None, now)
            t.done = True
            t.finish_reason = reason
            t.decode_tokens = int(tokens)
            t.add("retire", now, force=True, closed=closed,
                  ms=round(seg_ms, 3), reason=reason, tokens=tokens,
                  **attrs)
            reg = self._reg
            if reg is not None and tokens:
                per_tok = t.decode_ms / tokens
                reg.histogram("serve.decode_ms_per_token").observe(per_tok)
                if t.tenant:
                    reg.histogram(
                        f"serve.tenant[{t.tenant}].decode_ms_per_token"
                    ).observe(per_tok)
            self._finished.append(request_id)
            while len(self._finished) > self.capacity:
                rid = self._finished.popleft()
                old = self._traces.get(rid)
                if old is not None and old.done:
                    del self._traces[rid]
            payload = {"event": "serve_trace", "id": request_id,
                       **t.timeline()}
            payload.pop("request_id", None)
            emit = self._emit
        # outside the lock: a slow sink must not stall trace producers
        if emit is not None:
            emit(payload)

    # -- consumer surface --------------------------------------------------

    def get(self, request_id: str) -> Optional[RequestTrace]:
        with self._lock:
            return self._traces.get(request_id)

    def find(self, trace_id: str) -> List[RequestTrace]:
        """All traces carrying ``trace_id`` (a caller may submit many
        requests under one id via :func:`trace_context`)."""
        with self._lock:
            return [t for t in self._traces.values()
                    if t.trace_id == trace_id]

    def timeline(self, request_id: str) -> Optional[dict]:
        with self._lock:
            t = self._traces.get(request_id)
            return t.timeline() if t is not None else None


class SLOCapture:
    """SLO-triggered on-chip capture: evidence that collects itself.

    Attach to an engine (``Engine(slo_capture=SLOCapture(...))``); each
    non-empty step calls :meth:`on_step` (host-side only — no device
    interaction until a capture arms).  Every ``window_steps`` steps the
    rolling ``serve.ttft_ms`` p95 is compared against ``ttft_p95_ms``
    (needing ``min_samples`` observations first); ``windows`` CONSECUTIVE
    breached windows arm a bounded ``jax.profiler`` capture — via
    ``profiler.windowed_profiler`` — of the next ``capture_steps``
    steps into ``trace_dir/slo_capture_NNN``, then emit a
    ``serve_slo_capture`` event naming the directory.  ``max_captures``
    bounds the lifetime profile volume; breach counting resets after
    each capture and on any healthy window.

    ``profiler_factory(trace_dir)`` is injectable (tests); the default
    builds a started ``profiler.windowed_profiler``.
    """

    def __init__(self, ttft_p95_ms: float, trace_dir: str, *,
                 window_steps: int = 50, windows: int = 3,
                 capture_steps: int = 20, max_captures: int = 1,
                 min_samples: int = 8, profiler_factory=None):
        if ttft_p95_ms <= 0:
            raise ValueError(f"ttft_p95_ms must be > 0, got {ttft_p95_ms}")
        self.ttft_p95_ms = float(ttft_p95_ms)
        self.trace_dir = trace_dir
        self.window_steps = max(1, int(window_steps))
        self.windows = max(1, int(windows))
        self.capture_steps = max(1, int(capture_steps))
        self.max_captures = int(max_captures)
        self.min_samples = int(min_samples)
        self._factory = profiler_factory
        self._steps = 0
        self._breaches = 0
        self._prof = None
        self._remaining = 0
        self._dir: Optional[str] = None
        self._armed_p95: Optional[float] = None
        self.captures: List[str] = []   # finished capture directories

    @property
    def capturing(self) -> bool:
        return self._prof is not None

    def _ttft_p95(self) -> Optional[float]:
        from . import get_registry
        reg = get_registry()
        if reg is None:
            return None
        h = reg.get("serve.ttft_ms")
        if h is None or h.count < self.min_samples:
            return None
        return h.percentile(95)

    def _emit(self, **fields) -> None:
        emit = _state.EMIT[0]
        if emit is not None:
            emit({"event": "serve_slo_capture", **fields})

    def _arm(self, p95: float) -> None:
        d = os.path.join(self.trace_dir,
                         f"slo_capture_{len(self.captures):03d}")
        factory = self._factory
        if factory is None:
            from ..profiler import windowed_profiler
            factory = windowed_profiler
        self._prof = factory(d)
        self._remaining = self.capture_steps
        self._dir = d
        self._armed_p95 = p95
        from . import get_registry
        reg = get_registry()
        if reg is not None:
            reg.counter("serve.slo_captures").inc()
        self._emit(state="armed", trace_dir=d,
                   ttft_p95_ms=round(p95, 3),
                   threshold_ms=self.ttft_p95_ms,
                   breached_windows=self._breaches,
                   capture_steps=self.capture_steps)

    def _finish(self) -> None:
        prof, d = self._prof, self._dir
        self._prof = None
        self._dir = None
        self._breaches = 0
        try:
            prof.stop()
        except Exception:
            pass
        self.captures.append(d)
        self._emit(state="done", trace_dir=d,
                   ttft_p95_ms=self._armed_p95,
                   capture_steps=self.capture_steps)

    def on_step(self) -> None:
        """One engine step happened.  While capturing: count it down and
        stop the profiler at zero.  Otherwise: window bookkeeping +
        breach detection (a registry read every ``window_steps`` steps,
        nothing per step)."""
        if self._prof is not None:
            self._prof.step()
            self._remaining -= 1
            if self._remaining <= 0:
                self._finish()
            return
        self._steps += 1
        if self._steps % self.window_steps:
            return
        if len(self.captures) >= self.max_captures:
            return
        p95 = self._ttft_p95()
        if p95 is None:
            return                   # not enough signal: hold the count
        if p95 <= self.ttft_p95_ms:
            self._breaches = 0
            return
        self._breaches += 1
        if self._breaches >= self.windows:
            self._arm(p95)
