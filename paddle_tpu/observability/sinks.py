"""Telemetry sinks: where structured events go.

One event = one flat-ish JSON-serializable dict with at least ``event``
(kind) and ``ts`` (unix seconds, stamped by ``Telemetry.emit``).  Sinks
are deliberately dumb — no buffering policy beyond line-flush, no
schema enforcement — so a sink can never stall a train step for long,
and the JSONL stream stays greppable/tail-able while the job runs.

Multihost: ``enable()`` wraps file/stdout sinks in process-0 gating (see
``__init__.enable``); ``InMemorySink`` is never gated (tests assert on
every process).
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

__all__ = ["Sink", "InMemorySink", "JsonlSink", "StdoutSink"]


def _jsonable(v):
    """Best-effort scalarization: device arrays / numpy scalars become
    Python floats so a sink never triggers a surprising repr or keeps a
    buffer alive inside the event stream."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        return float(v)
    except Exception:
        return repr(v)


class Sink:
    """Interface: ``write(event_dict)`` + optional ``flush``/``close``."""

    def write(self, event: dict) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self.flush()


class InMemorySink(Sink):
    """Keeps events in memory — the test/inspection sink.

    ``maxlen`` bounds the buffer (oldest events dropped); ``enable()``'s
    default sink passes one so a sinkless long-running job cannot grow
    an event list without bound."""

    def __init__(self, maxlen: Optional[int] = None):
        from collections import deque
        self.records = deque(maxlen=maxlen)

    def write(self, event: dict) -> None:
        self.records.append(event)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        if kind is None:
            return list(self.records)
        return [e for e in self.records if e.get("event") == kind]

    def clear(self) -> None:
        self.records.clear()


class JsonlSink(Sink):
    """Appends one JSON line per event to ``path``.

    The file is opened lazily (first event) and flushed per line, so a
    preemption event emitted from a SIGTERM handler is on disk before the
    process exits, and ``tail -f`` sees steps as they happen.
    """

    def __init__(self, path: str):
        self.path = path
        self._fh = None

    def write(self, event: dict) -> None:
        if self._fh is None:
            self._fh = open(self.path, "a")
        # serialize fully, then ONE write call: a signal handler emitting
        # mid-write (preemption) must not interleave half-built lines
        self._fh.write(json.dumps(_jsonable(event),
                                  separators=(",", ":")) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class StdoutSink(Sink):
    """One JSON line per event to stderr by default.

    Default stream is *stderr*, not stdout: bench.py and the driver own a
    one-JSON-line-on-stdout contract that interleaved telemetry would
    corrupt.  Pass ``stream=sys.stdout`` explicitly to opt in.
    """

    def __init__(self, stream=None):
        self._stream = stream if stream is not None else sys.stderr

    def write(self, event: dict) -> None:
        self._stream.write(json.dumps(_jsonable(event),
                                      separators=(",", ":")) + "\n")
        self._stream.flush()


class _ProcessZeroGate(Sink):
    """Wraps a sink; drops events on non-zero processes (multihost: one
    JSONL stream per job, not per host, matching how the reference gates
    its logging on rank 0)."""

    def __init__(self, inner: Sink, is_zero: bool):
        self.inner = inner
        self._is_zero = is_zero

    def write(self, event: dict) -> None:
        if self._is_zero:
            self.inner.write(event)

    def flush(self) -> None:
        if self._is_zero:
            self.inner.flush()

    def close(self) -> None:
        if self._is_zero:
            self.inner.close()
