"""Telemetry sinks: where structured events go.

One event = one flat-ish JSON-serializable dict with at least ``event``
(kind) and ``ts`` (unix seconds, stamped by ``Telemetry.emit``).  Sinks
are deliberately dumb — no buffering policy beyond line-flush, no
schema enforcement — so a sink can never stall a train step for long,
and the JSONL stream stays greppable/tail-able while the job runs.

Multihost: ``enable()`` wraps file/stdout sinks in process-0 gating (see
``__init__.enable``); ``InMemorySink`` is never gated (tests assert on
every process).

This module also owns the registry→Prometheus text-exposition converter
(:func:`registry_to_prometheus`) and its name grammar
(:func:`prom_split`): bracketed registry names like
``serve.replica[0].free_blocks`` become labelled prom series
(``serve_replica_free_blocks{replica="0"}``) — the label KEY is the
dotted component carrying the bracket.  ``tools/telemetry_report.py``
loads this file standalone (no package import, no jax) and reuses the
same grammar, so the live ``/metrics`` surface and the offline report
cannot drift.  Keep this module stdlib-only with NO relative imports.
"""

from __future__ import annotations

import json
import re
import sys
from typing import List, Optional, Tuple

__all__ = ["Sink", "InMemorySink", "JsonlSink", "StdoutSink",
           "prom_name", "prom_split", "registry_to_prometheus"]


def _jsonable(v):
    """Best-effort scalarization: device arrays / numpy scalars become
    Python floats so a sink never triggers a surprising repr or keeps a
    buffer alive inside the event stream."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        return float(v)
    except Exception:
        return repr(v)


# -- Prometheus text exposition ---------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Sanitize one metric name into the Prometheus grammar
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``): every other char becomes ``_``."""
    n = _PROM_BAD.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def prom_split(name: str) -> Tuple[str, List[Tuple[str, str]]]:
    """Split a registry name into ``(prom_name, [(label_key, value)])``.

    ``serve.replica[0].free_blocks`` → ``("serve_replica_free_blocks",
    [("replica", "0")])``; the label key is the dotted component the
    bracket is attached to (``serve.tenant[acme].requests`` →
    ``tenant="acme"``, ``span[ckpt.save].ms`` → ``span="ckpt.save"``).
    Bracket content containing ``=`` is the fleet-fold grammar
    (``observability/aggregate.py``): explicit comma-separated label
    pairs — ``serve.ttft_ms[worker=w0,role=decode]`` →
    ``("serve_ttft_ms", [("worker", "w0"), ("role", "decode")])`` — so
    per-worker series and the unlabelled fleet rollup share one prom
    family.  Unbracketed names pass through with no labels.
    """
    labels: List[Tuple[str, str]] = []
    out: List[str] = []
    rest = name
    while True:
        i = rest.find("[")
        if i < 0:
            out.append(rest)
            break
        j = rest.find("]", i)
        if j < 0:                       # unbalanced: treat as literal
            out.append(rest)
            break
        head = rest[:i]
        out.append(head)
        content = rest[i + 1:j]
        if "=" in content:
            for part in content.split(","):
                k, _, v = part.partition("=")
                labels.append((prom_name(k.strip()) or "label",
                               v.strip()))
        else:
            key = head.rsplit(".", 1)[-1]
            labels.append((prom_name(key) or "label", content))
        rest = rest[j + 1:]
    base = "".join(out).strip(".")
    return prom_name(base), labels


def _prom_escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _prom_num(v):
    """A renderable sample value, or None (prom samples are numbers)."""
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)):
        return v
    return None


def registry_to_prometheus(registry=None, extra=None) -> str:
    """Render a ``MetricsRegistry`` as Prometheus text exposition
    (version 0.0.4) — the body of the serving server's ``GET /metrics``.

    Counters/gauges render as their kind; histograms render as
    summaries (``_count``/``_sum`` plus ``quantile="0.5"/"0.95"``
    samples from the rolling window).  Metric kinds are duck-typed
    (``inc``/``set``/``observe``) so this module stays standalone.
    Gauges holding non-numeric values are skipped.  ``extra`` is a
    ``{registry_name: value}`` dict of engine-local gauges appended for
    names the registry does not already carry — the fallback surface
    when telemetry is disabled.
    """
    # prom pname -> (kind, [(suffix, labels, value)]); grouped so every
    # series emits ONE # TYPE line followed by all its samples
    groups: dict = {}
    order: List[str] = []

    def _add(pname, kind, suffix, labels, value):
        v = _prom_num(value)
        if v is None:
            return
        g = groups.get(pname)
        if g is None:
            groups[pname] = g = (kind, [])
            order.append(pname)
        elif g[0] != kind:
            return       # post-sanitation kind collision: first wins
        g[1].append((suffix, labels, v))

    names = registry.names() if registry is not None else []
    for name in names:
        m = registry.get(name)
        if m is None:
            continue
        pname, labels = prom_split(name)
        if hasattr(m, "observe"):
            snap = m.snapshot()
            _add(pname, "summary", "_count", labels, snap.get("count"))
            _add(pname, "summary", "_sum", labels, snap.get("sum"))
            for q, key in (("0.5", "p50"), ("0.95", "p95")):
                _add(pname, "summary", "", labels + [("quantile", q)],
                     snap.get(key))
        elif hasattr(m, "inc"):
            _add(pname, "counter", "", labels, m.snapshot())
        else:
            _add(pname, "gauge", "", labels, m.snapshot())
    have = set(names)
    for name, value in sorted((extra or {}).items()):
        if name in have:
            continue                 # live registry series wins
        pname, labels = prom_split(name)
        _add(pname, "gauge", "", labels, value)

    lines: List[str] = []
    for pname in order:
        kind, samples = groups[pname]
        if not samples:
            continue
        lines.append(f"# TYPE {pname} {kind}")
        for suffix, labels, v in samples:
            lbl = ""
            if labels:
                lbl = "{" + ",".join(
                    f'{k}="{_prom_escape(val)}"' for k, val in labels) \
                    + "}"
            lines.append(f"{pname}{suffix}{lbl} {v}")
    return "\n".join(lines) + ("\n" if lines else "")


class Sink:
    """Interface: ``write(event_dict)`` + optional ``flush``/``close``."""

    def write(self, event: dict) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self.flush()


class InMemorySink(Sink):
    """Keeps events in memory — the test/inspection sink.

    ``maxlen`` bounds the buffer (oldest events dropped); ``enable()``'s
    default sink passes one so a sinkless long-running job cannot grow
    an event list without bound."""

    def __init__(self, maxlen: Optional[int] = None):
        from collections import deque
        self.records = deque(maxlen=maxlen)

    def write(self, event: dict) -> None:
        self.records.append(event)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        if kind is None:
            return list(self.records)
        return [e for e in self.records if e.get("event") == kind]

    def clear(self) -> None:
        self.records.clear()


class JsonlSink(Sink):
    """Appends one JSON line per event to ``path``.

    The file is opened lazily (first event) and flushed per line, so a
    preemption event emitted from a SIGTERM handler is on disk before the
    process exits, and ``tail -f`` sees steps as they happen.
    """

    def __init__(self, path: str):
        self.path = path
        self._fh = None

    def write(self, event: dict) -> None:
        if self._fh is None:
            self._fh = open(self.path, "a")
        # serialize fully, then ONE write call: a signal handler emitting
        # mid-write (preemption) must not interleave half-built lines
        self._fh.write(json.dumps(_jsonable(event),
                                  separators=(",", ":")) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class StdoutSink(Sink):
    """One JSON line per event to stderr by default.

    Default stream is *stderr*, not stdout: bench.py and the driver own a
    one-JSON-line-on-stdout contract that interleaved telemetry would
    corrupt.  Pass ``stream=sys.stdout`` explicitly to opt in.
    """

    def __init__(self, stream=None):
        self._stream = stream if stream is not None else sys.stderr

    def write(self, event: dict) -> None:
        self._stream.write(json.dumps(_jsonable(event),
                                      separators=(",", ":")) + "\n")
        self._stream.flush()


class _ProcessZeroGate(Sink):
    """Wraps a sink; drops events on non-zero processes (multihost: one
    JSONL stream per job, not per host, matching how the reference gates
    its logging on rank 0)."""

    def __init__(self, inner: Sink, is_zero: bool):
        self.inner = inner
        self._is_zero = is_zero

    def write(self, event: dict) -> None:
        if self._is_zero:
            self.inner.write(event)

    def flush(self) -> None:
        if self._is_zero:
            self.inner.flush()

    def close(self) -> None:
        if self._is_zero:
            self.inner.close()
