"""StepMonitor: per-step wall time, tokens/sec, MFU — one event per step.

Fed by the three training front doors (``jit.TrainStep.__call__``,
``hapi.Model._train_one``, and therefore ``distributed.Engine.fit``,
which drives a TrainStep) through the one-falsy-check hook in
``_state.MONITOR``.

Timing protocol — why two durations per event:

- ``wall_ms``: dispatch-to-return of this call.  Under jax's async
  dispatch this can undershoot the real step time until the pipeline
  backpressures (the host runs ahead), and the first call absorbs the
  XLA compile.
- ``interval_ms``: end-to-end time since the previous step of the same
  site.  In steady state this is exactly what bench.py measures (a
  timed loop over steps), so ``tokens_per_sec`` and ``mfu`` are derived
  from the interval once one exists — runtime numbers and bench numbers
  share both the clock protocol and the flops formula (``mfu.py``).

Warmup events (first ``warmup_steps`` per site — the compile) are
emitted but flagged ``"warmup": true`` so report tooling excludes them
from throughput aggregates.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from . import _state
from .mfu import flops_per_token_of, peak_flops
from .spans import span

__all__ = ["StepMonitor"]


def _first_array(batch):
    """The leaf whose shape defines the token count: ``input_ids`` when
    present (the LM convention bench.py uses), else the first
    shaped leaf found."""
    if hasattr(batch, "shape"):
        return batch
    if isinstance(batch, dict):
        ids = batch.get("input_ids")
        if hasattr(ids, "shape"):
            return ids
        for v in batch.values():
            if hasattr(v, "shape"):
                return v
    if isinstance(batch, (list, tuple)):
        for v in batch:
            a = _first_array(v)
            if a is not None:
                return a
    return None


def _tokens_of(batch):
    """(tokens, seq_len) from the batch's leading array: B·S for ndim≥2
    (seq = dim 1), B for ndim 1, None when nothing is shaped."""
    arr = _first_array(batch)
    if arr is None or not getattr(arr, "shape", None):
        return None, None
    shape = arr.shape
    if len(shape) >= 2:
        return int(shape[0]) * int(shape[1]), int(shape[1])
    return int(shape[0]), None


class StepMonitor:
    """Emits one ``step`` event per training step through the Telemetry
    sinks and mirrors the numbers into the registry."""

    def __init__(self, telemetry, registry, sentinel=None,
                 warmup_steps: int = 1):
        self._tel = telemetry
        self._reg = registry
        self.sentinel = sentinel
        self.warmup_steps = int(warmup_steps)
        self.total_steps = 0
        self.last_event: Optional[dict] = None
        self._sites: dict = {}   # site -> {"steps", "last_t", "fpt", "fpt_seq"}

    # -- hot-path entry points --------------------------------------------

    def timed_step(self, site: str, model, batch,
                   thunk: Callable[[], Any]):
        """Run one training step under timing + compile attribution.

        The step runs inside an ``emit=False`` span: the ``step`` event
        already carries the numbers, but the span's ``span_begin``
        breadcrumb (BEFORE the thunk — a wedged step must beat on entry,
        then go visibly silent) feeds the flight recorder / hang
        watchdog, and the profiler bridge puts the site name on the
        chrome-trace host timeline while a Profiler is recording.
        """
        sent = self.sentinel
        t0 = time.perf_counter()
        with span(site, emit=False):
            if sent is not None:
                with sent.site(site):
                    out = thunk()
            else:
                out = thunk()
        t1 = time.perf_counter()
        self._record(site, model, batch, t0, t1)
        return out

    def compile_site(self, site: str):
        """Attribution-only scope for non-step jit entries (to_static)."""
        if self.sentinel is not None:
            return self.sentinel.site(site)
        import contextlib
        return contextlib.nullcontext()

    # -- accounting --------------------------------------------------------

    def _record(self, site, model, batch, t0, t1):
        info = self._sites.get(site)
        if info is None:
            info = self._sites[site] = {
                "steps": 0, "last_t": None, "fpt": None, "fpt_seq": None}
        info["steps"] += 1
        self.total_steps += 1
        n = info["steps"]
        wall_s = t1 - t0
        interval_s = (t1 - info["last_t"]) if info["last_t"] is not None \
            else wall_s
        info["last_t"] = t1
        ev = {"event": "step", "site": site, "step": n,
              "wall_ms": round(wall_s * 1e3, 3),
              "interval_ms": round(interval_s * 1e3, 3),
              "warmup": n <= self.warmup_steps}
        tokens, seq = _tokens_of(batch)
        if tokens:
            tps = tokens / interval_s if interval_s > 0 else 0.0
            ev["tokens"] = tokens
            ev["tokens_per_sec"] = round(tps, 1)
            fpt = self._flops_per_token(info, model, seq)
            if fpt:
                ev["mfu"] = round(tps * fpt / peak_flops(), 4)
        self.last_event = ev
        reg = self._reg
        if reg is not None:
            reg.counter(f"step[{site}].count").inc()
            if not ev["warmup"]:
                reg.histogram(f"step[{site}].interval_ms").observe(
                    interval_s * 1e3)
                if "tokens_per_sec" in ev:
                    reg.gauge(f"step[{site}].tokens_per_sec").set(
                        ev["tokens_per_sec"])
                if "mfu" in ev:
                    reg.gauge(f"step[{site}].mfu").set(ev["mfu"])
                # roofline attribution: measured interval vs this
                # site's compiled-program analytic minimum (ledger
                # rows land under the SAME site string because
                # timed_step wraps the thunk in sent.site(site)).
                # Unlike mfu this also sees the bandwidth-bound limit,
                # so a memory-bound step can read 0.9 roofline at 0.1
                # MFU — that gap IS the diagnosis.
                led = _state.LEDGER[0]
                if led is not None:
                    min_ms = led.min_ms_for(site)
                    if min_ms and interval_s > 0:
                        ev["roofline_frac"] = round(
                            min_ms / (interval_s * 1e3), 4)
                        reg.gauge(f"train.roofline[{site}].frac").set(
                            ev["roofline_frac"])
                        reg.gauge(f"train.roofline[{site}].min_ms").set(
                            round(min_ms, 6))
        self._tel.emit(ev)

    def _flops_per_token(self, info, model, seq):
        # cached per site; recomputed only if the seq length changes
        # (shape churn — which the sentinel is already yelling about)
        if info["fpt"] is None or info["fpt_seq"] != seq:
            info["fpt"] = flops_per_token_of(model, seq)
            info["fpt_seq"] = seq
        return info["fpt"]
