"""Flight recorder + crash post-mortems: the half of observability that
works when the job is NOT making progress.

The JSONL sinks (``sinks.py``) report on healthy runs; the failure mode
that actually burns multihost TPU time is the job that silently stops —
a wedged collective, a host stuck in data loading, a preemption that
kills the process mid-step.  The reference capability (PaddlePaddle's
profiler/monitor stack, SURVEY §5.5) demands that when that happens, the
artifacts to diagnose it are already on disk.

Two pieces:

- **FlightRecorder**: a fixed-size in-memory ring that passively records
  the last N telemetry events plus lightweight breadcrumbs (span
  begin/end around steps, collectives, ckpt I/O; compile events).  One
  deque append per record when enabled — CPython deque appends are
  atomic, so producers on the trainer thread and the compile listener
  never contend on a lock.  The newest append also stamps ``last_beat``
  (monotonic), which is the liveness signal the hang watchdog polls.
- **Post-mortems**: ``write_postmortem`` drains every thread's stack
  (``sys._current_frames``), the ring, and a registry snapshot to a
  ``*.postmortem`` JSONL file in ONE buffered write + fsync.  It is
  called by the hang watchdog, ``launch.PreemptionGuard`` (first
  SIGTERM), an unhandled-exception hook, an ``atexit`` hook (covers
  ``sys.exit`` mid-run and forgotten ``disable()``), and a SIGQUIT
  handler (``kill -QUIT`` = dump-without-dying, the classic flight-
  recorder convention).  It never raises: it runs in crash context.

Pure stdlib; ``tools/telemetry_report.py`` reads the post-mortem file
with the same JSONL parser as a telemetry stream.  Schema:
docs/OBSERVABILITY.md ("Crash post-mortems").
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, List, Optional

from . import _state
from .sinks import _jsonable

__all__ = ["FlightRecorder", "write_postmortem", "install_crash_hooks",
           "uninstall_crash_hooks"]


class FlightRecorder:
    """Bounded ring of the last ``capacity`` events/breadcrumbs."""

    __slots__ = ("capacity", "_ring", "last_beat", "total")

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self.last_beat = time.monotonic()
        self.total = 0          # lifetime appends (ring drops the oldest)

    def record(self, kind: str, **fields) -> None:
        """One breadcrumb: dict build + ONE deque append, no lock."""
        self._ring.append({"ts": round(time.time(), 3), "event": kind,
                           **fields})
        self.total += 1
        self.last_beat = time.monotonic()

    def record_event(self, event: dict) -> None:
        """Append an already-built telemetry event (Telemetry.emit path)."""
        self._ring.append(event)
        self.total += 1
        self.last_beat = time.monotonic()

    def age_s(self) -> float:
        """Seconds since the last recorded event — the liveness signal."""
        return time.monotonic() - self.last_beat

    def snapshot(self) -> List[dict]:
        # list() of a deque is safe against concurrent appends
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


# ---------------------------------------------------------------------------
# post-mortem writing
# ---------------------------------------------------------------------------

# session defaults, set by observability.enable() via configure_postmortem;
# last_reason remembers that a post-mortem was already written this
# session so the atexit drain doesn't overwrite a targeted dump
# (exception/hang/preemption) with a generic end-of-process one
_PM = {"path": None, "recorder": None, "registry_fn": None,
       "last_reason": None}

DEFAULT_POSTMORTEM_PATH = "run.postmortem"


def configure_postmortem(path: Optional[str],
                         recorder: Optional[FlightRecorder] = None,
                         registry_fn: Optional[Callable[[], dict]] = None
                         ) -> None:
    """Bind the session's post-mortem destination + sources, and expose
    ``write_postmortem`` through the ``_state.POSTMORTEM`` hook so signal
    handlers (preemption) reach it without imports."""
    _PM.update(path=path, recorder=recorder, registry_fn=registry_fn)
    _state.POSTMORTEM[0] = write_postmortem


def _reset_postmortem() -> None:
    _PM.update(path=None, recorder=None, registry_fn=None,
               last_reason=None)
    _state.POSTMORTEM[0] = None


def _thread_stacks() -> List[dict]:
    """One ``thread_stack`` record per live thread, from the outside —
    this is how a hang dump shows WHERE the wedged thread is stuck."""
    threads = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        t = threads.get(tid)
        out.append({
            "event": "thread_stack",
            "thread": t.name if t is not None else str(tid),
            "thread_id": tid,
            "daemon": bool(t.daemon) if t is not None else None,
            "frames": [ln.rstrip("\n")
                       for ln in traceback.format_stack(frame)],
        })
    return out


def write_postmortem(reason: str = "unknown", path: Optional[str] = None,
                     recorder: Optional[FlightRecorder] = None,
                     registry_fn: Optional[Callable[[], dict]] = None,
                     exc=None, extra: Optional[dict] = None
                     ) -> Optional[str]:
    """Drain thread stacks + flight ring + registry snapshot to ``path``.

    Returns the path written, or None on failure — it NEVER raises (the
    callers are signal handlers, excepthooks, and a watchdog looking at
    a process that is already in trouble).  The file is rewritten whole
    each call (newest post-mortem wins) with one buffered write + fsync,
    so even a SIGKILL right after still leaves a complete file.
    """
    try:
        path = path or _PM["path"] or DEFAULT_POSTMORTEM_PATH
        recorder = recorder if recorder is not None \
            else (_PM["recorder"] or _state.RECORDER[0])
        registry_fn = registry_fn or _PM["registry_fn"]

        head = {"event": "postmortem", "reason": reason,
                "ts": round(time.time(), 3), "pid": os.getpid()}
        if exc is not None:
            etype, value, tb = exc
            head["exception"] = {
                "type": getattr(etype, "__name__", str(etype)),
                "message": str(value),
                "traceback": [ln.rstrip("\n") for ln in
                              traceback.format_exception(etype, value, tb)],
            }
        if extra:
            head.update(extra)
        lines = [head]
        lines.extend(_thread_stacks())
        led = _state.LEDGER[0]
        if led is not None:
            # the compiled-program cost/memory rows + the last HBM pool
            # snapshot: an OOM/stall dump names which program or pool
            # owned the bytes.  Pure host-side copies — never touches a
            # device buffer from a dying process.
            lines.append({"event": "compiled_artifacts",
                          "rows": led.snapshot(), "hbm": led.hbm})
        if recorder is not None:
            lines.append({"event": "flight_recorder",
                          "recorded": len(recorder),
                          "total": recorder.total,
                          "capacity": recorder.capacity,
                          "last_beat_age_s": round(recorder.age_s(), 3)})
            lines.extend(recorder.snapshot())
        if registry_fn is not None:
            # snapshot on a helper thread with a timeout: this runs from
            # signal handlers (preemption/SIGQUIT), and the interrupted
            # frame may HOLD a non-reentrant per-metric lock — a direct
            # registry_fn() would self-deadlock the dying process (the
            # same reentrancy hazard Telemetry.emit's RLock guards).  On
            # timeout the dump proceeds without the metrics line.
            got = []
            try:
                t = threading.Thread(
                    target=lambda: got.append(registry_fn()), daemon=True)
                t.start()
                t.join(timeout=2.0)
            except Exception:
                pass
            if got:
                lines.append({"event": "metrics", "metrics": got[0]})
            else:
                lines.append({"event": "metrics_unavailable",
                              "reason": "registry snapshot timed out "
                                        "(lock held by the interrupted "
                                        "thread?)"})
        buf = "\n".join(json.dumps(_jsonable(l), separators=(",", ":"))
                        for l in lines) + "\n"
        with open(path, "w") as f:
            f.write(buf)
            f.flush()
            os.fsync(f.fileno())
        _PM["last_reason"] = reason
        return path
    except Exception:
        return None


# ---------------------------------------------------------------------------
# crash hooks: atexit + unhandled exception + SIGQUIT
# ---------------------------------------------------------------------------

_HOOKS = {"installed": False, "prev_excepthook": None, "sigquit": False,
          "prev_sigquit": None, "in_excepthook": False}


def _atexit_drain() -> None:
    # a targeted dump (unhandled exception, hang, preemption) already on
    # disk wins over a generic end-of-process drain
    if _HOOKS["installed"] and _PM["last_reason"] is None:
        write_postmortem(reason="atexit")


def _crash_excepthook(etype, value, tb) -> None:
    # reentrancy guard: a third party may have chained over us across an
    # uninstall/reinstall cycle, putting this function in its own prev
    # chain — loop once, then fall through to the interpreter default
    if _HOOKS["in_excepthook"]:
        sys.__excepthook__(etype, value, tb)
        return
    _HOOKS["in_excepthook"] = True
    try:
        # like _atexit_drain: a clean disable() must mean no dump, even
        # if a chaining third party still routes exceptions through us
        if _HOOKS["installed"]:
            write_postmortem(reason="unhandled_exception",
                             exc=(etype, value, tb))
        prev = _HOOKS["prev_excepthook"] or sys.__excepthook__
        prev(etype, value, tb)
    finally:
        _HOOKS["in_excepthook"] = False


def _sigquit_handler(signum, frame) -> None:
    # dump-without-dying: operators `kill -QUIT` a suspicious job to get
    # stacks + the ring on disk, and the job keeps running
    write_postmortem(reason="SIGQUIT")


def install_crash_hooks(path: Optional[str] = None,
                        recorder: Optional[FlightRecorder] = None,
                        registry_fn: Optional[Callable[[], dict]] = None,
                        sigquit: bool = True) -> None:
    """Arrange for the ring to be drained on every exit the interpreter
    can still see: ``atexit`` (covers ``sys.exit`` mid-run and a run
    that never called ``disable()``), unhandled exceptions, and SIGQUIT.
    Idempotent; ``observability.disable()`` uninstalls."""
    if path or recorder or registry_fn:
        configure_postmortem(path or _PM["path"],
                             recorder or _PM["recorder"],
                             registry_fn or _PM["registry_fn"])
    if _HOOKS["installed"]:
        return
    _HOOKS["installed"] = True
    atexit.register(_atexit_drain)
    _HOOKS["prev_excepthook"] = sys.excepthook
    sys.excepthook = _crash_excepthook
    if sigquit and hasattr(signal, "SIGQUIT") \
            and threading.current_thread() is threading.main_thread():
        try:
            prev = signal.getsignal(signal.SIGQUIT)
            if prev == signal.SIG_DFL:   # never clobber a user handler
                signal.signal(signal.SIGQUIT, _sigquit_handler)
                _HOOKS["sigquit"] = True
                _HOOKS["prev_sigquit"] = prev
        except (ValueError, OSError):
            pass


def uninstall_crash_hooks() -> None:
    if not _HOOKS["installed"]:
        return
    _HOOKS["installed"] = False
    try:
        atexit.unregister(_atexit_drain)
    except Exception:
        pass
    if sys.excepthook is _crash_excepthook:
        sys.excepthook = _HOOKS["prev_excepthook"] or sys.__excepthook__
        _HOOKS["prev_excepthook"] = None
    # else: a third party chained over us — leave prev_excepthook bound
    # so the still-reachable _crash_excepthook keeps forwarding to the
    # user's original hook (it will not write: installed is False)
    if _HOOKS["sigquit"]:
        try:
            signal.signal(signal.SIGQUIT, _HOOKS["prev_sigquit"])
        except (ValueError, OSError):
            pass
        _HOOKS["sigquit"] = False
        _HOOKS["prev_sigquit"] = None
