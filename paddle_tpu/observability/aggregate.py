"""Fleet-mergeable telemetry: histogram sketches, wire snapshots, folds.

The single-process registry (``registry.py``) keeps a rolling ring per
histogram — right for one worker's "current regime" p95, useless for a
fleet: percentiles do not average, so a controller holding ten workers'
p95s cannot produce the fleet p95.  This module is the mergeable half:

- :class:`HistogramSketch` — fixed log-spaced buckets shared by every
  sketch in the fleet, so ``merge`` is element-wise addition and is
  associative/commutative by construction.  Fleet p95 is computed from
  the MERGED sketch, never from averaged per-worker p95s.
- :func:`registry_to_wire` — one worker's registry as a JSON-able
  snapshot (counters/gauges by value, histograms by sketch), the
  payload workers publish to ``telemetry/<wid>`` store keys.
- :func:`fleet_fold` — per-worker wire snapshots folded into one
  :class:`FleetRegistry` carrying per-worker-labelled series
  (``serve.ttft_ms[worker=w0,role=decode]``), per-role tier rollups and
  unlabelled fleet rollups; duck-typed so
  ``sinks.registry_to_prometheus`` renders it unchanged.
- :func:`stitch_trace_segments` — per-worker ``serve_trace`` segments
  of one request (prefill worker + decode worker, split by a cross-host
  KV handoff) joined into one timeline on the controller's timebase,
  with per-worker clock-skew correction; each segment's exact-sum phase
  invariant is preserved verbatim, and inter-segment gaps are
  attributed to ``xfer``.

Keep this module stdlib-only with NO relative imports:
``tools/telemetry_report.py`` and ``tools/trace_export.py`` load it
standalone (``importlib``, no package import, no jax), the same
contract ``sinks.py`` honors, so the live controller surface and the
offline tools cannot drift.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["HistogramSketch", "FleetRegistry", "fleet_fold",
           "registry_to_wire", "stitch_trace_segments"]

# Bucket geometry — a module constant so every sketch in the fleet (and
# every release that keeps this table) merges element-wise.  16 buckets
# per decade over [1e-3, 1e7) covers microsecond phase times through
# multi-hour walls with a per-bucket width of 10^(1/16) ≈ 1.155, i.e. a
# worst-case relative quantile error of ~15.5% (typically half that);
# index 0 is the underflow bucket (v <= 1e-3, including zeros), the
# last index the overflow bucket (v > 1e7).
BUCKETS_PER_DECADE = 16
_MIN_EXP = -3
_MAX_EXP = 7
_CORE = (_MAX_EXP - _MIN_EXP) * BUCKETS_PER_DECADE
NUM_BUCKETS = _CORE + 2                  # + underflow + overflow


def _bucket_index(v: float) -> int:
    if v <= 10.0 ** _MIN_EXP:
        return 0
    x = (math.log10(v) - _MIN_EXP) * BUCKETS_PER_DECADE
    if x >= _CORE:
        return NUM_BUCKETS - 1
    # strictly-greater lower edge: a value exactly on a bucket's lower
    # bound belongs to that bucket's predecessor's successor — int(x)
    # floors, +1 skips the underflow slot
    return min(int(x) + 1, _CORE)


def _bucket_upper(i: int) -> float:
    """Upper bound of core bucket ``i`` (1..CORE)."""
    return 10.0 ** (_MIN_EXP + i / BUCKETS_PER_DECADE)


class HistogramSketch:
    """Fixed-bucket log-spaced histogram; merge = element-wise add.

    Lifetime (not rolling) on purpose: merged fleet series must be
    monotone so scrapes at different instants stay comparable; the
    rolling "current regime" view stays the per-worker ring's job.
    ``percentile`` is nearest-rank over the cumulative bucket counts,
    answering with the bucket's upper bound clamped into the exact
    observed ``[min, max]`` — a single-value sketch reports that value
    exactly, and the error bound is one bucket width.
    """

    __slots__ = ("_counts", "_count", "_sum", "_min", "_max", "_lock")

    def __init__(self):
        self._counts: Dict[int, int] = {}
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = _bucket_index(v)
        with self._lock:
            self._counts[i] = self._counts.get(i, 0) + 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def merge(self, other: "HistogramSketch") -> "HistogramSketch":
        """Fold ``other`` into self (returns self).  Element-wise over
        the shared bucket table: associative and commutative, the
        property that makes fleet percentiles well-defined no matter
        which controller folds which worker first."""
        with other._lock:
            counts = dict(other._counts)
            cnt, tot = other._count, other._sum
            mn, mx = other._min, other._max
        with self._lock:
            for i, n in counts.items():
                self._counts[i] = self._counts.get(i, 0) + n
            self._count += cnt
            self._sum += tot
            if mn is not None and (self._min is None or mn < self._min):
                self._min = mn
            if mx is not None and (self._max is None or mx > self._max):
                self._max = mx
        return self

    def copy(self) -> "HistogramSketch":
        return HistogramSketch().merge(self)

    def percentile(self, p: float) -> Optional[float]:
        with self._lock:
            if not self._count:
                return None
            rank = max(1, math.ceil(p / 100.0 * self._count))
            seen = 0
            for i in sorted(self._counts):
                seen += self._counts[i]
                if seen >= rank:
                    if i == 0:
                        v = self._min
                    elif i == NUM_BUCKETS - 1:
                        v = self._max
                    else:
                        v = _bucket_upper(i)
                    if v is None:    # foreign wire without min/max
                        v = _bucket_upper(max(min(i, _CORE), 1))
                    if self._min is not None:
                        v = max(v, self._min)
                    if self._max is not None:
                        v = min(v, self._max)
                    return v
        return self._max

    def snapshot(self) -> dict:
        """Same shape as ``registry.Histogram.snapshot`` so the prom
        exporter's summary rendering applies unchanged."""
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
        out = {"count": count, "sum": round(total, 6)}
        if count:
            out.update(mean=round(total / count, 6),
                       p50=self.percentile(50), p95=self.percentile(95),
                       max=mx)
        return out

    # -- wire format ---------------------------------------------------------

    def to_dict(self) -> dict:
        """JSON-able snapshot (sparse buckets, keys stringified for
        JSON round-trips)."""
        with self._lock:
            return {"count": self._count, "sum": self._sum,
                    "min": self._min, "max": self._max,
                    "buckets": {str(i): n
                                for i, n in sorted(self._counts.items())}}

    @classmethod
    def from_dict(cls, d: dict) -> "HistogramSketch":
        sk = cls()
        sk._count = int(d.get("count") or 0)
        sk._sum = float(d.get("sum") or 0.0)
        sk._min = None if d.get("min") is None else float(d["min"])
        sk._max = None if d.get("max") is None else float(d["max"])
        for k, n in (d.get("buckets") or {}).items():
            i = int(k)
            if 0 <= i < NUM_BUCKETS and int(n) > 0:
                sk._counts[i] = sk._counts.get(i, 0) + int(n)
        return sk


# ---------------------------------------------------------------------------
# registry wire snapshots
# ---------------------------------------------------------------------------

def registry_to_wire(registry) -> Dict[str, dict]:
    """One registry as a JSON-able ``{name: {"kind": ..., ...}}`` dict —
    counters/gauges by value, histograms by their mergeable sketch.
    Duck-typed (``sketch``/``inc``/``observe``) so it works on the real
    :class:`~paddle_tpu.observability.MetricsRegistry` and on fakes.
    Gauges holding non-numeric values are skipped (same rule as the
    prom exporter)."""
    out: Dict[str, dict] = {}
    for name in registry.names():
        m = registry.get(name)
        if m is None:
            continue
        sk = getattr(m, "sketch", None)
        if sk is not None:
            out[name] = {"kind": "sketch", **sk.to_dict()}
        elif hasattr(m, "observe"):
            continue                # sketchless histogram: not mergeable
        elif hasattr(m, "inc"):
            out[name] = {"kind": "counter", "value": m.snapshot()}
        else:
            v = m.snapshot()
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, (int, float)):
                out[name] = {"kind": "gauge", "value": v}
    return out


# ---------------------------------------------------------------------------
# the fleet fold
# ---------------------------------------------------------------------------

class _CounterView:
    """Read-mostly counter view (``inc`` marks the prom kind)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str, value=0):
        self.name = name
        self.value = value

    def inc(self, n=1) -> None:
        self.value += n

    def snapshot(self):
        return self.value


class _GaugeView:
    __slots__ = ("name", "value")

    def __init__(self, name: str, value=None):
        self.name = name
        self.value = value

    def set(self, v) -> None:
        self.value = v

    def snapshot(self):
        return self.value


class _SketchView:
    """Sketch wrapper; ``observe`` marks the prom summary kind and the
    snapshot carries the merged p50/p95."""

    __slots__ = ("name", "sketch")

    def __init__(self, name: str, sketch: Optional[HistogramSketch] = None):
        self.name = name
        self.sketch = sketch if sketch is not None else HistogramSketch()

    def observe(self, v: float) -> None:
        self.sketch.observe(v)

    def percentile(self, p: float):
        return self.sketch.percentile(p)

    def snapshot(self) -> dict:
        return self.sketch.snapshot()


class FleetRegistry:
    """A read-only registry of fold views, duck-type compatible with
    ``sinks.registry_to_prometheus`` (``names``/``get`` plus per-metric
    ``inc``/``observe``/``snapshot``)."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def snapshot(self) -> Dict[str, object]:
        return {n: m.snapshot()
                for n, m in sorted(self._metrics.items())}

    # fold surface ----------------------------------------------------------

    def _counter(self, name: str) -> _CounterView:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = _CounterView(name)
        return m

    def _gauge(self, name: str) -> _GaugeView:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = _GaugeView(name)
        return m

    def _sketch(self, name: str) -> _SketchView:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = _SketchView(name)
        return m


def _label_value(v) -> str:
    """Bracket-content sanitization: the fleet grammar's reserved chars
    cannot appear inside a label value."""
    s = str(v)
    for ch in "[],=":
        s = s.replace(ch, "_")
    return s


def _labeled(name: str, pairs: List[Tuple[str, str]]) -> str:
    lbl = ",".join(f"{k}={_label_value(v)}" for k, v in pairs)
    return f"{name}[{lbl}]"


def fleet_fold(snapshots: Dict[str, dict]) -> FleetRegistry:
    """Fold per-worker wire snapshots (``{wid: {"role": ...,
    "metrics": {name: wire}}}`` — the ``telemetry/<wid>`` payloads)
    into one :class:`FleetRegistry`:

    - ``name[worker=<wid>,role=<role>]`` — per-worker series,
    - ``name[role=<role>]`` — tier rollup (counters/gauges summed,
      sketches merged),
    - ``name`` — fleet rollup.

    Sketch percentiles in the rollups come from the MERGED buckets;
    gauge rollups are sums (additive gauges — queue depth, tok/s, KV
    blocks — are the fleet reading; non-additive ones are still exact
    in their per-worker series)."""
    fleet = FleetRegistry()
    for wid in sorted(snapshots):
        snap = snapshots[wid] or {}
        role = snap.get("role") or "?"
        per_worker = [("worker", wid), ("role", role)]
        per_role = [("role", role)]
        for name in sorted(snap.get("metrics") or {}):
            wire = snap["metrics"][name]
            kind = wire.get("kind")
            if kind == "counter":
                v = wire.get("value") or 0
                fleet._counter(_labeled(name, per_worker)).inc(v)
                fleet._counter(_labeled(name, per_role)).inc(v)
                fleet._counter(name).inc(v)
            elif kind == "gauge":
                v = wire.get("value")
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool):
                    continue
                fleet._gauge(_labeled(name, per_worker)).set(v)
                g = fleet._gauge(_labeled(name, per_role))
                g.set((g.value or 0) + v)
                g = fleet._gauge(name)
                g.set((g.value or 0) + v)
            elif kind == "sketch":
                sk = HistogramSketch.from_dict(wire)
                fleet._sketch(_labeled(name, per_worker)).sketch \
                    .merge(sk)
                fleet._sketch(_labeled(name, per_role)).sketch \
                    .merge(sk)
                fleet._sketch(name).sketch.merge(sk)
    return fleet


# ---------------------------------------------------------------------------
# cross-host trace stitching
# ---------------------------------------------------------------------------

def stitch_trace_segments(segments: List[dict]) -> Optional[dict]:
    """Join one request's per-worker ``serve_trace`` segments into one
    timeline on the controller's timebase.

    Each segment is a tracer ``timeline()`` payload plus the worker's
    shipping envelope (``worker``/``role``/``epoch``/``clock_offset``,
    where ``clock_offset`` = worker wall clock − controller wall clock
    as estimated from store round-trips).  Segments are ordered by
    skew-corrected start time; every segment's own phase accounting is
    preserved verbatim (its exact-sum invariant is per-clock and must
    not be re-derived across hosts), and each positive inter-segment
    gap — the cross-host KV handoff window — is added to the stitched
    ``xfer_ms``.  The stitched wall is DEFINED as the sum of segment
    walls plus positive gaps, so the top-level phase sums reproduce the
    exact-sum invariant by construction; ``monotonic`` reports whether
    the corrected segments were in fact non-overlapping (a false value
    means residual skew beyond the correction).
    """
    if not segments:
        return None

    def _summary(seg: dict) -> dict:
        return seg.get("summary") or {}

    corr = []
    for seg in segments:
        t0 = float(seg.get("t0") or 0.0)
        off = float(seg.get("clock_offset") or 0.0)
        start = t0 - off
        wall = float(_summary(seg).get("wall_ms") or 0.0)
        corr.append((start, seg.get("worker") or "?", seg, wall))
    corr.sort(key=lambda c: (c[0], c[1]))

    phases = {"queue_ms": 0.0, "prefill_ms": 0.0, "xfer_ms": 0.0,
              "decode_ms": 0.0}
    out_segs: List[dict] = []
    monotonic = True
    gap_total = 0.0
    prev_end = None
    for start, _, seg, wall in corr:
        s = _summary(seg)
        for k in phases:
            phases[k] += float(s.get(k) or 0.0)
        if prev_end is not None:
            gap_ms = (start - prev_end) * 1e3
            if gap_ms < -0.5:        # > rounding noise: residual skew
                monotonic = False
            gap_ms = max(gap_ms, 0.0)
            phases["xfer_ms"] += gap_ms
            gap_total += gap_ms
        prev_end = start + wall / 1e3
        out_segs.append({"worker": seg.get("worker"),
                         "role": seg.get("role"),
                         "epoch": seg.get("epoch"),
                         "start": round(start, 6),
                         "end": round(prev_end, 6),
                         "clock_offset": seg.get("clock_offset") or 0.0,
                         "summary": dict(s),
                         "events": [dict(e)
                                    for e in seg.get("events") or []]})

    head = corr[0][2]
    tail = corr[-1][2]
    phases = {k: round(v, 3) for k, v in phases.items()}
    last = _summary(tail)
    return {"id": head.get("id") or head.get("request_id"),
            "trace_id": head.get("trace_id"),
            "tenant": head.get("tenant"),
            "segments": out_segs,
            "hosts": sorted({s["worker"] for s in out_segs
                             if s["worker"]}),
            "xfer_gap_ms": round(gap_total, 3),
            "monotonic": monotonic,
            "reason": last.get("reason"),
            "decode_tokens": last.get("decode_tokens"),
            **phases,
            "wall_ms": round(sum(phases.values()), 3)}
