"""MFU accounting shared by bench.py and the runtime StepMonitor.

THE single source of the flops-per-token formula and the per-chip peak
table: bench numbers (BENCH_r*.json) and runtime telemetry events agree
by construction because both call these functions — a change here moves
both, a change nowhere else can split them.

Accounting convention (docs/BENCH.md): causal-LM training flops/token =
``6N + 6·L·h·T`` — 6N for the parameter matmuls (fwd+bwd), causal
attention credited at half the s² matmul.  Recompute is never credited
(an honest MFU carries the remat tax).
"""

from __future__ import annotations

import functools
from typing import Optional

__all__ = ["PEAK_BF16_FLOPS", "peak_flops", "causal_lm_flops_per_token",
           "dense_flops_per_token", "flops_per_token_of"]


PEAK_BF16_FLOPS = {
    # per-chip peak bf16 FLOP/s
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e
    "cpu": 1e12,             # nominal, CI only
}


@functools.lru_cache(maxsize=1)
def peak_flops() -> float:
    """Peak bf16 FLOP/s of device 0's chip kind (1e12 nominal on CPU)."""
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu")
    for k, v in PEAK_BF16_FLOPS.items():
        if kind.startswith(k):
            return v
    return PEAK_BF16_FLOPS.get(kind, 197e12)


def causal_lm_flops_per_token(n_params: int, num_layers: int,
                              hidden_size: int, seq_len: int) -> float:
    """Causal-attention-aware model flops per trained token: 6N + 6·L·h·T."""
    return 6.0 * n_params + 6.0 * num_layers * hidden_size * seq_len


def dense_flops_per_token(n_params: int) -> float:
    """Attention-less fallback (6N) for models without a transformer
    config — an MFU floor, exact for pure-MLP workloads."""
    return 6.0 * n_params


def flops_per_token_of(model, seq_len: Optional[int]) -> Optional[float]:
    """Best-effort flops/token for an arbitrary model.

    Transformer configs (``model.cfg`` with ``num_params``/
    ``num_hidden_layers``/``hidden_size`` — the llama/gpt shape) get the
    full causal formula; any other Layer gets the 6N floor; a model with
    no countable parameters returns None (the step event then simply
    omits ``mfu``).
    """
    cfg = getattr(model, "cfg", None)
    if (cfg is not None and seq_len and callable(getattr(cfg, "num_params", None))
            and hasattr(cfg, "num_hidden_layers") and hasattr(cfg, "hidden_size")):
        return causal_lm_flops_per_token(cfg.num_params(),
                                         cfg.num_hidden_layers,
                                         cfg.hidden_size, seq_len)
    params = getattr(model, "parameters", None)
    if callable(params):
        try:
            n = sum(int(p.size) for p in params())
        except Exception:
            return None
        return dense_flops_per_token(n) if n else None
    return None
