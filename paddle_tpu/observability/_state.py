"""Hot-path hook containers — the whole disabled-telemetry surface.

Mirrors ``distributed/debug.py``'s zero-overhead contract: a producer on
a hot path does ONE falsy check against a module-level container::

    hook = _obs_state.MONITOR[0]
    if hook is not None:
        ...telemetry path...

With telemetry disabled (the default) every container holds ``None`` and
the check costs ~0.2 µs — no lock, no dict, no registry, no import of
anything heavier than this (stdlib-free) module.  ``enable()`` /
``disable()`` in ``observability/__init__`` are the only writers.

Containers are single-element lists (not bare globals) so hot modules
can bind the list object once at import time and still observe
enable/disable flips.
"""

# StepMonitor instance, or None. Read by jit.TrainStep.__call__,
# jit.to_static dispatch, hapi.Model._train_one.
MONITOR = [None]

# callable(op_name, axes, first_arg) or None. Read by
# distributed.communication's _traced wrapper per collective call.
COLLECTIVE = [None]

# callable(event_dict) (Telemetry.emit) or None. Read by
# launch.preempt's signal handler and distributed.Engine.fit.
EMIT = [None]

# FlightRecorder instance, or None. Read by cold-path breadcrumb
# producers (ckpt save/load, the watchdog, crash hooks); hot paths feed
# it through MONITOR/SPAN so their disabled cost stays one falsy check.
RECORDER = [None]

# spans._SpanHook instance, or None. Read by every ``span(...)`` scope
# (ckpt, Engine.fit epochs, eager collectives, jit AOT export).
SPAN = [None]

# callable(reason=...) -> path|None (flight_recorder.write_postmortem)
# or None. Read by launch.preempt's signal handler so a preempted run
# drains the flight-recorder ring without importing anything inside a
# signal frame.
POSTMORTEM = [None]

# trace.RequestTracer instance, or None. Read by every serving
# request-lifecycle site (FrontDoor.submit, Engine admission/step/
# preempt/restore/retire, EngineReplicaSet routing/evacuation) — the
# per-request timeline producer (observability/trace.py).
TRACE = [None]

# compiled.CompiledArtifactLedger instance, or None. Read by the
# serve/train roofline gauge producers (Engine.step_finish,
# StepMonitor._record) and the HBM gauge publisher (Engine.warmup) —
# the compile-time capture itself rides a method wrap installed only
# while telemetry is enabled, so it has NO disabled-path check at all.
LEDGER = [None]
