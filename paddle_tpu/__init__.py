"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built from scratch on jax/XLA/Pallas.

Architecture (see SURVEY.md for the reference map):
- ``paddle_tpu.nn``         Layer system + layer zoo + functional ops
- ``paddle_tpu.ops``        tensor-op API (paddle.* parity) + Pallas kernel registry
- ``paddle_tpu.optimizer``  optimizers / LR schedulers as pure pytree transforms
- ``paddle_tpu.amp``        bf16/fp16 mixed precision, GradScaler, O2 decorate
- ``paddle_tpu.autograd``   grad façade, PyLayer (custom_vjp)
- ``paddle_tpu.jit``        step compiler (to_static→jax.jit), TrainStep, AOT export
- ``paddle_tpu.distributed``fleet hybrid-parallel (dp/mp/pp/sharding/sep/ep),
                            collectives over ICI, auto-parallel shard_tensor
- ``paddle_tpu.io``         Dataset/DataLoader/DistributedBatchSampler
- ``paddle_tpu.ckpt``       sharded checkpoint save/load with reshard-on-load
- ``paddle_tpu.profiler``   jax.profiler façade (chrome trace export)
- ``paddle_tpu.observability`` always-on runtime telemetry (step metrics,
                            recompile sentinel, collective accounting)
- ``paddle_tpu.models``     in-repo model zoo (llama, gpt/ernie, mixtral-moe, sdxl-unet)
"""

__version__ = "0.1.0"

import jax as _jax

from . import core
from .core import (Tensor, bfloat16, bool_, device_count, float16, float32,  # noqa: F401
                   float64, get_default_dtype, get_device, get_flags, int8,
                   int16, int32, int64, is_compiled_with_cuda, seed,
                   set_default_dtype, set_device, set_flags, synchronize,
                   to_tensor, uint8)
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import jit  # noqa: F401
from . import ops  # noqa: F401
from .nn.layer import ParamAttr  # noqa: F401

# paddle.* tensor-op namespace parity: re-export the ops module surface.
from .ops import *  # noqa: F401,F403
# linalg/fft as real importable modules (reference: python/paddle/linalg.py)
from . import linalg, fft  # noqa: F401

# random ops at top level (paddle.rand / paddle.normal / ...)
from .ops import (rand, randn, randint, uniform, normal, randperm,  # noqa: F401
                  bernoulli, multinomial)


def save(obj, path, **kw):
    """``paddle.save`` parity (see paddle_tpu.ckpt)."""
    from . import ckpt as _ckpt
    return _ckpt.save(obj, path, **kw)


def load(path, **kw):
    """``paddle.load`` parity (see paddle_tpu.ckpt)."""
    from . import ckpt as _ckpt
    return _ckpt.load(path, **kw)


def no_grad():
    return autograd.no_grad()


def grad(*a, **k):
    return autograd.grad(*a, **k)


# lazily-imported heavyweight submodules
def __getattr__(name):
    import importlib
    if name in ("distributed", "io", "ckpt", "models", "profiler", "metrics",
                "vision", "incubate", "hapi", "static", "device", "launch",
                "utils", "config", "sparse", "quantization", "inference",
                "audio", "distribution", "geometric", "signal", "regularizer",
                "callbacks", "text", "hub", "onnx", "observability",
                "resilience", "serving"):
        try:
            mod = importlib.import_module(f".{name}", __name__)
        except ModuleNotFoundError as e:
            # keep hasattr()/getattr() probing working for not-yet-built
            # submodules
            raise AttributeError(
                f"module 'paddle_tpu' has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    if name == "Model":  # paddle.Model lives in hapi
        from .hapi import Model
        globals()["Model"] = Model
        return Model
    if name == "DataParallel":  # the class itself: isinstance/subclass work
        from .distributed.parallel import DataParallel
        globals()["DataParallel"] = DataParallel
        return DataParallel
    if name == "flops":  # paddle.flops lives in hapi (dynamic_flops)
        from .hapi import flops
        globals()["flops"] = flops
        return flops
    if name == "summary":  # paddle.summary lives in hapi (model_summary)
        from .hapi import summary
        globals()["summary"] = summary
        return summary
    if name == "version":
        import importlib
        mod = importlib.import_module(".version", __name__)
        globals()["version"] = mod
        return mod
    if name in ("enable_static", "disable_static", "in_dynamic_mode"):
        from . import static as _static
        fn = getattr(_static, name)
        globals()[name] = fn
        return fn
    if name == "metric":  # paddle.metric alias
        from . import metrics
        globals()["metric"] = metrics
        return metrics
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def __dir__():
    # surface the lazily-resolved names so dir()/introspection (and the
    # api-compat spec scanner) see the full public surface
    return sorted(set(globals()) | {
        "distributed", "io", "ckpt", "models", "profiler", "metrics",
        "vision", "incubate", "hapi", "static", "device", "launch", "utils",
        "config", "sparse", "quantization", "inference", "audio",
        "distribution", "geometric", "signal", "regularizer", "callbacks",
        "text", "hub", "onnx", "observability", "resilience", "serving",
        "Model", "DataParallel", "flops", "summary", "version", "metric",
        "enable_static", "disable_static", "in_dynamic_mode"})


def tensor(data, dtype=None, place=None, stop_gradient=True):
    """``paddle.tensor`` alias of ``to_tensor`` (reference accepts both)."""
    return to_tensor(data, dtype=dtype, place=place,
                     stop_gradient=stop_gradient)


def is_tensor(x) -> bool:
    import numpy as _np
    return isinstance(x, (_jax.Array, _np.ndarray))


def iinfo(dtype):
    import jax.numpy as _jnp
    return _jnp.iinfo(core.convert_dtype(dtype))


def finfo(dtype):
    import jax.numpy as _jnp
    return _jnp.finfo(core.convert_dtype(dtype))


def get_rng_state():
    """Reference: paddle.get_rng_state — opaque state restorable with
    set_rng_state (here the (seed, eager-draw counter) pair)."""
    from .core import random as _r
    return (_r._GLOBAL_SEED[0], _r._EAGER_COUNTER[0])


def set_rng_state(state):
    from .core import random as _r
    _r._GLOBAL_SEED[0], _r._EAGER_COUNTER[0] = int(state[0]), int(state[1])


def is_grad_enabled() -> bool:
    return autograd.is_grad_enabled()


def set_grad_enabled(mode: bool):
    return autograd.set_grad_enabled(mode)


# the Place CLASSES themselves (isinstance works, like DataParallel above);
# CUDAPlace/XPUPlace alias the accelerator place — the accelerator is the TPU
from .device import CPUPlace, CUDAPinnedPlace, TPUPlace  # noqa: F401,E402

CUDAPlace = TPUPlace
XPUPlace = TPUPlace

# dtype OBJECTS at the top level (reference: paddle.bool / paddle.complex64
# / paddle.dtype).  `bool` intentionally shadows the builtin inside this
# namespace only, exactly as the reference does; `dtype` is the type of a
# Tensor's .dtype attribute so `isinstance(x.dtype, paddle.dtype)` ports.
import numpy as _np  # noqa: E402

bool = bool_  # noqa: A001
complex64 = _np.complex64
complex128 = _np.complex128
dtype = _np.dtype


def enable_grad():
    return autograd.enable_grad()


# CUDA-prefixed rng-state API: the reference keeps separate host/device rng
# streams; here one global stream drives both (documented deviation)
def get_cuda_rng_state():
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)


def to_dlpack(x):
    """Reference: paddle.utils.dlpack.to_dlpack / paddle.to_dlpack.

    DLPack is a host/GPU interchange protocol; TPU HBM buffers are not
    dlpack-addressable, so device arrays are staged through host memory
    first (one copy — same as the reference's GPU→consumer-on-CPU path).
    Returns a modern-protocol exporter object (implements ``__dlpack__``),
    which every current consumer (``torch.from_dlpack``,
    ``np.from_dlpack``, this module's ``from_dlpack``) accepts; the legacy
    raw-capsule form is not produced."""
    if isinstance(x, _jax.Array):
        try:
            x.__dlpack_device__()  # raises for TPU-resident buffers
            return x
        except Exception:  # BufferError / runtime UNIMPLEMENTED
            # depending on the PJRT plugin: stage via host.  np.asarray on
            # a jax array yields a readonly view — copy so export works.
            return _np.array(x)
    return _np.asarray(x)


def from_dlpack(ext_array):
    """Accepts any object implementing the DLPack exchange protocol
    (``__dlpack__``/``__dlpack_device__``) — torch/NumPy/jax arrays or
    the object ``to_dlpack`` returns."""
    from jax import dlpack as _dl
    return _dl.from_dlpack(ext_array)


def LazyGuard():
    """Reference: paddle.LazyGuard — construct layers without materialising
    parameters.  TPU-native analogue: nn.layer.meta_init() (parameters
    become ShapeDtypeStructs; lower/compile works, eager exec does not)."""
    from .nn.layer import meta_init
    return meta_init()


# paddle Tensor METHOD surface (x.abs(), x.unsqueeze(0), x.add_(y), ...)
# installed onto jax.Array + Tracer — see core/tensor_methods.py
from .core import tensor_methods as _tensor_methods  # noqa: E402

_tensor_methods.install()
