"""``paddle.quantization`` parity: QAT fake-quant + PTQ observers.

Reference: python/paddle/quantization/ (QuantConfig, QAT, PTQ,
FakeQuanterWithAbsMaxObserver, AbsmaxObserver) — SURVEY §2.6.

TPU redesign: fake-quant is a straight-through-estimator round in the
compiled graph (XLA fuses it into adjacent ops); QAT wraps Linear/Conv2D
with weight (and optional activation) fake-quant. int8 inference conversion
(`convert`) materializes quantized weights + scales.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..nn.layers_common import Conv2D, Linear

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMax",
           "AbsmaxObserver", "quantize_absmax", "dequantize"]


def _ste_round(x):
    """Round with straight-through gradient."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


def quantize_absmax(x, bits: int = 8, axis=None):
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
    scale = jnp.maximum(scale, 1e-8) / qmax
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax).astype(jnp.int8)
    return q, scale


def dequantize(q, scale):
    return q.astype(jnp.float32) * scale


class FakeQuanterWithAbsMax(Layer):
    """Simulated quantization: quantize→dequantize with STE gradients."""

    def __init__(self, bits: int = 8, axis=None):
        super().__init__()
        self.bits = bits
        self.axis = axis

    def forward(self, x):
        qmax = 2.0 ** (self.bits - 1) - 1
        scale = jnp.max(jnp.abs(jax.lax.stop_gradient(x)), axis=self.axis,
                        keepdims=self.axis is not None)
        scale = jnp.maximum(scale, 1e-8) / qmax
        return _ste_round(x / scale).clip(-qmax - 1, qmax) * scale


class AbsmaxObserver(Layer):
    """PTQ observer: tracks running max |x| to derive scales offline."""

    def __init__(self, bits: int = 8):
        super().__init__()
        self.bits = bits
        self.register_buffer("absmax", jnp.zeros(()), persistable=True)

    def forward(self, x):
        self.absmax = jnp.maximum(self.absmax, jnp.max(jnp.abs(x)))
        return x

    def scale(self):
        qmax = 2.0 ** (self.bits - 1) - 1
        return jnp.maximum(self.absmax, 1e-8) / qmax


@dataclasses.dataclass
class QuantConfig:
    """Which layer types get quantized and how many bits."""

    weight_bits: int = 8
    activation_bits: int = 8
    quantize_activations: bool = False
    layer_types: tuple = (Linear, Conv2D)

    def add_type_config(self, layer_type, weight_bits=None):
        self.layer_types = (*self.layer_types, layer_type)


class _QuantWrapper(Layer):
    """Wraps a layer: fake-quant its weight (and optionally input)."""

    def __init__(self, inner: Layer, config: QuantConfig):
        super().__init__()
        self.inner = inner
        self.wq = FakeQuanterWithAbsMax(config.weight_bits)
        self.aq = (FakeQuanterWithAbsMax(config.activation_bits)
                   if config.quantize_activations else None)

    def forward(self, x):
        if self.aq is not None:
            x = self.aq(x)
        w = self.inner.weight
        try:
            self.inner.weight = self.wq(self.inner.weight)
            return self.inner(x)
        finally:
            self.inner.weight = w


class QAT:
    """Quantization-aware training driver: model → fake-quantized model."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._rewrite(model)
        return model

    def _rewrite(self, layer: Layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, self.config.layer_types):
                layer._sub_layers[name] = _QuantWrapper(sub, self.config)
            else:
                self._rewrite(sub)

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        """Materialize int8 weights + scales for inference export."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def conv(layer):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, _QuantWrapper):
                    q, scale = quantize_absmax(sub.inner.weight,
                                               self.config.weight_bits)
                    sub.inner.weight = dequantize(q, scale)
                    sub.inner.register_buffer("weight_scale", scale)
                    sub.inner.register_buffer("weight_int8", q)
                    layer._sub_layers[name] = sub.inner
                else:
                    conv(sub)

        conv(model)
        return model


class PTQ:
    """Post-training quantization: observe activations, then convert."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        qat = QAT(self.config)
        return qat.quantize(model, inplace=inplace)

    convert = QAT.convert
