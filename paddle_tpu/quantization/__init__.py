"""``paddle.quantization`` parity: QAT fake-quant + PTQ observers.

Reference: python/paddle/quantization/ (QuantConfig, QAT, PTQ,
FakeQuanterWithAbsMaxObserver, AbsmaxObserver) — SURVEY §2.6.

TPU redesign: fake-quant is a straight-through-estimator round in the
compiled graph (XLA fuses it into adjacent ops); QAT wraps Linear/Conv2D
with weight (and optional activation) fake-quant. int8 inference conversion
(`convert`) materializes quantized weights + scales.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..nn.layers_common import Conv2D, Linear

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMax",
           "AbsmaxObserver", "quantize_absmax", "dequantize"]


def _ste_round(x):
    """Round with straight-through gradient."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


def _int_dtype(bits: int):
    if bits <= 8:
        return jnp.int8
    if bits <= 16:
        return jnp.int16
    return jnp.int32


def quantize_absmax(x, bits: int = 8, axis=None):
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
    scale = jnp.maximum(scale, 1e-8) / qmax
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax).astype(_int_dtype(bits))
    return q, scale


def dequantize(q, scale):
    return q.astype(jnp.float32) * scale


class FakeQuanterWithAbsMax(Layer):
    """Simulated quantization: quantize→dequantize with STE gradients."""

    def __init__(self, bits: int = 8, axis=None):
        super().__init__()
        self.bits = bits
        self.axis = axis

    def forward(self, x):
        qmax = 2.0 ** (self.bits - 1) - 1
        scale = jnp.max(jnp.abs(jax.lax.stop_gradient(x)), axis=self.axis,
                        keepdims=self.axis is not None)
        scale = jnp.maximum(scale, 1e-8) / qmax
        return _ste_round(x / scale).clip(-qmax - 1, qmax) * scale


class AbsmaxObserver(Layer):
    """PTQ observer: tracks running max |x| to derive scales offline.

    Calibration is a HOST-side pass (eager forwards over calibration data);
    running it under jax.jit would leak a tracer into the buffer, so that
    is rejected explicitly."""

    def __init__(self, bits: int = 8):
        super().__init__()
        self.bits = bits
        self.register_buffer("absmax", jnp.zeros(()), persistable=True)

    def forward(self, x):
        import jax.core
        if isinstance(x, jax.core.Tracer):
            raise RuntimeError(
                "AbsmaxObserver calibration must run eagerly (outside "
                "jax.jit) — the running absmax is host state")
        self.absmax = jnp.maximum(self.absmax, jnp.max(jnp.abs(x)))
        return x

    def scale(self):
        qmax = 2.0 ** (self.bits - 1) - 1
        return jnp.maximum(self.absmax, 1e-8) / qmax


@dataclasses.dataclass
class QuantConfig:
    """Which layer types get quantized and how many bits."""

    weight_bits: int = 8
    activation_bits: int = 8
    quantize_activations: bool = False
    layer_types: tuple = (Linear, Conv2D)
    type_bits: Dict[type, int] = dataclasses.field(default_factory=dict)

    def add_type_config(self, layer_type, weight_bits=None):
        self.layer_types = (*self.layer_types, layer_type)
        if weight_bits is not None:
            self.type_bits[layer_type] = weight_bits

    def bits_for(self, layer) -> int:
        # most-specific match wins: walk the MRO so a subclass's own config
        # beats its base class's, regardless of insertion order
        for t in type(layer).__mro__:
            if t in self.type_bits:
                return self.type_bits[t]
        return self.weight_bits


class _QuantWrapper(Layer):
    """Wraps a layer: fake-quant its weight (and optionally input)."""

    def __init__(self, inner: Layer, config: QuantConfig):
        super().__init__()
        self.inner = inner
        self.weight_bits = config.bits_for(inner)
        self.wq = FakeQuanterWithAbsMax(self.weight_bits)
        self.aq = (FakeQuanterWithAbsMax(config.activation_bits)
                   if config.quantize_activations else None)

    def forward(self, x):
        if self.aq is not None:
            x = self.aq(x)
        w = self.inner.weight
        try:
            self.inner.weight = self.wq(self.inner.weight)
            return self.inner(x)
        finally:
            self.inner.weight = w


class QAT:
    """Quantization-aware training driver: model → fake-quantized model."""

    wrapper_cls = _QuantWrapper

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._rewrite(model)
        return model

    def _rewrite(self, layer: Layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, self.config.layer_types):
                # setattr (NOT a raw _sub_layers write) so the owner's
                # instance attribute used by its forward() is replaced too
                setattr(layer, name, type(self).wrapper_cls(sub, self.config))
            else:
                self._rewrite(sub)

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        """Materialize integer weights + scales for inference export."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def conv(layer):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, _QuantWrapper):
                    q, scale = quantize_absmax(sub.inner.weight,
                                               sub.weight_bits)
                    sub.inner.weight = dequantize(q, scale)
                    sub.inner.register_buffer("weight_scale", scale)
                    # named by role, not dtype: int16/int32 for wide bits
                    sub.inner.register_buffer("weight_quant", q)
                    if getattr(sub, "observer", None) is not None:
                        sub.inner.register_buffer("act_scale",
                                                  sub.observer.scale())
                    setattr(layer, name, sub.inner)
                else:
                    conv(sub)

        conv(model)
        return model


class _ObserverWrapper(_QuantWrapper):
    """PTQ wrapper: TRANSPARENT forward (no fake-quant perturbation) with an
    input-activation observer — post-training calibration semantics."""

    def __init__(self, inner: Layer, config: QuantConfig):
        super().__init__(inner, config)
        self.aq = None
        self.observer = AbsmaxObserver(config.activation_bits)

    def forward(self, x):
        self.observer(x)
        return self.inner(x)


class PTQ(QAT):
    """Post-training quantization: observe activations eagerly over
    calibration data, then ``convert`` (weights absmax-quantized, observed
    activation scales attached as ``act_scale`` buffers). Same driver as
    QAT with a transparent observer wrapper instead of fake-quant."""

    wrapper_cls = _ObserverWrapper


class FakeQuanterChannelWiseAbsMax(FakeQuanterWithAbsMax):
    """Per-output-channel scales (reference:
    FakeQuanterChannelWiseAbsMaxObserver) — axis 0 of the weight by
    default, matching the reference's channel-wise weight quant."""

    def __init__(self, bits: int = 8, quant_axis: int = 0):
        # reduce over every axis EXCEPT the channel axis
        super().__init__(bits=bits, axis=None)
        self.quant_axis = quant_axis

    def forward(self, x):
        qmax = 2.0 ** (self.bits - 1) - 1
        red = tuple(i for i in range(x.ndim) if i != self.quant_axis)
        scale = jnp.max(jnp.abs(jax.lax.stop_gradient(x)), axis=red,
                        keepdims=True)
        scale = jnp.maximum(scale, 1e-8) / qmax
        return _ste_round(x / scale).clip(-qmax - 1, qmax) * scale


class MovingAverageAbsmaxObserver(AbsmaxObserver):
    """EMA absmax (reference: MovingAverageAbsmaxObserver) — smoother than
    the running max for long calibration streams."""

    def __init__(self, bits: int = 8, moving_rate: float = 0.9):
        super().__init__(bits=bits)
        self.moving_rate = moving_rate

    def forward(self, x):
        import jax.core
        if isinstance(x, jax.core.Tracer):
            raise RuntimeError(
                "observer calibration must run eagerly (outside jax.jit)")
        cur = jnp.max(jnp.abs(x))
        self.absmax = jnp.where(
            self.absmax == 0.0, cur,
            self.moving_rate * self.absmax + (1 - self.moving_rate) * cur)
        return x


__all__ += ["FakeQuanterChannelWiseAbsMax", "MovingAverageAbsmaxObserver"]


class BaseQuanter(Layer):
    """Reference: paddle.quantization.BaseQuanter — the abstract fake-
    quant node contract (FakeQuanterWithAbsMax implements it)."""

    def forward(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None

    def quant_axis(self):
        return None

    def bit_length(self):
        return 8


class BaseObserver(Layer):
    """Reference: paddle.quantization.BaseObserver — statistics
    collectors for PTQ calibration (AbsmaxObserver implements it)."""

    def forward(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError


def quanter(name):
    """Reference: paddle.quantization.quanter — class decorator that
    registers a quanter under a config-referencable name."""
    def wrap(cls):
        _QUANTER_REGISTRY[name] = cls
        cls._quanter_name = name
        return cls
    return wrap


_QUANTER_REGISTRY = {"FakeQuanterWithAbsMax": FakeQuanterWithAbsMax,
                     "AbsmaxObserver": AbsmaxObserver}

__all__ += ["BaseQuanter", "BaseObserver", "quanter"]
