"""``paddle.incubate.optimizer`` parity: LookAhead, ModelAverage.

Reference: python/paddle/incubate/optimizer/lookahead.py (slow/fast
weights, k-step interpolation) and modelaverage.py (running parameter
average applied for eval, restored for training).

TPU redesign: both are pure wrappers over the inner optimizer's
functional (init/apply) core, so they compose into the jitted TrainStep
unchanged — the k-step LookAhead sync is a ``jnp.where`` on
``step % k == 0`` (no host branch), ModelAverage keeps the running
average as extra state and ``apply_average``/``restore`` are functional.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """slow += alpha * (fast - slow) every k steps; fast := slow then."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        # surface parity with the wrapped optimizer
        self.grad_clip = getattr(inner_optimizer, "grad_clip", None)
        self.multi_precision = getattr(inner_optimizer, "multi_precision",
                                       False)

    def init(self, params):
        state = {"inner": self.inner.init(params),
                 "slow": {k: v for k, v in params.items()},
                 "la_step": jnp.zeros((), jnp.int32)}
        return state

    def apply(self, grads, state, params):
        new_params, inner_state = self.inner.apply(grads, state["inner"],
                                                   params)
        la_step = state["la_step"] + 1
        sync = (la_step % self.k) == 0
        out_params: Dict[str, jax.Array] = {}
        new_slow: Dict[str, jax.Array] = {}
        for name, fast in new_params.items():
            slow = state["slow"][name]
            synced = slow.astype(jnp.float32) + self.alpha * (
                fast.astype(jnp.float32) - slow.astype(jnp.float32))
            synced = synced.astype(fast.dtype)
            new_slow[name] = jnp.where(sync, synced, slow)
            out_params[name] = jnp.where(sync, synced, fast)
        return out_params, {"inner": inner_state, "slow": new_slow,
                            "la_step": la_step}


class ModelAverage:
    """Maintain a running average of parameters; swap it in for eval.

    Reference window semantics (paddle.incubate.ModelAverage /
    average_accumulates kernel): the accumulation window is
    ``min(max_average_window, max(min_average_window, rate * num_updates))``.
    When the current block fills the window it rolls into an ``old`` block
    (rather than being dropped), so the average is always backed by at
    least one full window of history around restarts.
    """

    def __init__(self, inner_optimizer, average_window_rate=0.15,
                 min_average_window=10000, max_average_window=20000):
        self.inner = inner_optimizer
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self.grad_clip = getattr(inner_optimizer, "grad_clip", None)
        self.multi_precision = getattr(inner_optimizer, "multi_precision",
                                       False)

    def init(self, params):
        zeros = {k: jnp.zeros_like(v, jnp.float32)
                 for k, v in params.items()}
        return {"inner": self.inner.init(params),
                "sum": zeros,
                "old_sum": dict(zeros),
                "num": jnp.zeros((), jnp.int32),
                "old_num": jnp.zeros((), jnp.int32),
                "updates": jnp.zeros((), jnp.int32)}

    def apply(self, grads, state, params):
        new_params, inner_state = self.inner.apply(grads, state["inner"],
                                                   params)
        updates = state["updates"] + 1
        num = state["num"] + 1
        window = jnp.minimum(
            jnp.int32(self.max_w),
            jnp.maximum(jnp.int32(self.min_w),
                        (self.rate * updates).astype(jnp.int32)))
        roll = num >= window
        new_sum, new_old_sum = {}, {}
        for name, p in new_params.items():
            s = state["sum"][name] + p.astype(jnp.float32)
            new_old_sum[name] = jnp.where(roll, s, state["old_sum"][name])
            new_sum[name] = jnp.where(roll, jnp.zeros_like(s), s)
        return new_params, {
            "inner": inner_state, "sum": new_sum, "old_sum": new_old_sum,
            "num": jnp.where(roll, jnp.int32(0), num),
            "old_num": jnp.where(roll, num, state["old_num"]),
            "updates": updates}

    def average_params(self, state, params):
        """→ averaged params for evaluation (reference: apply())."""
        n = jnp.maximum(state["num"] + state["old_num"], 1).astype(
            jnp.float32)
        return {k: ((state["sum"][k] + state["old_sum"][k]) / n).astype(
            v.dtype) for k, v in params.items()}
