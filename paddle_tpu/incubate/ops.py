"""paddle_tpu.incubate op tail: fused masked softmax, identity_loss,
graph sampling re-exports.

Reference: python/paddle/incubate/operators/*.py.  The "fused" masked
softmaxes are single jitted expressions — XLA fuses mask-add + softmax
into one HBM pass, which is the entire point of the reference's custom
CUDA kernels (SURVEY §7.0 dissolution stance).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=())
def softmax_mask_fuse(x, mask):
    """Reference: incubate.softmax_mask_fuse — softmax(x + mask) in one
    fused pass; x (B, H, S, S), mask broadcastable (B, 1, S, S)."""
    return jax.nn.softmax(x + mask.astype(x.dtype), axis=-1)


@jax.jit
def softmax_mask_fuse_upper_triangle(x):
    """Reference: incubate.softmax_mask_fuse_upper_triangle — causal
    (lower-triangular-visible) masked softmax without materialising the
    mask in HBM."""
    s = x.shape[-1]
    causal = jnp.tril(jnp.ones((s, s), bool))
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, x.dtype)
    return jax.nn.softmax(jnp.where(causal, x, neg), axis=-1)


def identity_loss(x, reduction="none"):
    """Reference: paddle.incubate.identity_loss — mark a value as the
    loss with an optional reduction (int codes 0/1/2 = sum/mean/none)."""
    if isinstance(reduction, int):
        reduction = {0: "sum", 1: "mean", 2: "none"}[reduction]
    x = jnp.asarray(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "none":
        return x
    raise ValueError("reduction must be sum/mean/none or 0/1/2")
