"""``paddle.incubate.nn`` parity: fused transformer layers for inference.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiTransformer — the whole-decoder-layer fused op with cached-KV
attention, backed by FusedMultiTransformerKernel, SURVEY §2.1).
"""

from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer import Layer
from ...nn.layers_common import LayerList
from . import functional  # noqa: F401
from .functional import (decode_attend_cache, masked_multihead_attention,
                         prefill_write_cache, read_cache_prefix)


class FusedMultiTransformer(Layer):
    """Inference-oriented decoder stack with dense KV caches.

    One call runs ALL layers (the reference fuses the whole decoder stack
    into one op); under jit the prefill path and the one-token decode path
    each compile to a single XLA program. Pre-norm, rotary embeddings,
    GQA, SwiGLU or GELU FFN — covering the reference kernel's config space
    that matters on TPU.
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, num_layers,
                 num_kv_heads=None, activation="swiglu", epsilon=1e-5,
                 normalize_before=True, norm_type="rmsnorm",
                 rope_theta=10000.0):
        super().__init__()
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.activation = activation
        self.epsilon = epsilon
        self.norm_type = norm_type
        self.rope_theta = rope_theta
        from ...nn.layers_common import Linear, RMSNorm, LayerNorm
        Norm = RMSNorm if norm_type == "rmsnorm" else LayerNorm
        kv_dim = self.num_kv_heads * self.head_dim
        ffn_mult = 2 if activation == "swiglu" else 1
        self._layers = []
        for i in range(num_layers):
            blk = Layer()
            blk.ln_attn = Norm(embed_dim, epsilon=epsilon)
            blk.qkv_proj = Linear(embed_dim, embed_dim + 2 * kv_dim,
                                  bias_attr=False)
            blk.out_proj = Linear(embed_dim, embed_dim, bias_attr=False)
            blk.ln_ffn = Norm(embed_dim, epsilon=epsilon)
            blk.ffn1 = Linear(embed_dim, dim_feedforward * ffn_mult,
                              bias_attr=False)
            blk.ffn2 = Linear(dim_feedforward, embed_dim, bias_attr=False)
            self.add_sublayer(f"layer_{i}", blk)
            self._layers.append(blk)

    def init_cache(self, batch, max_len, dtype=jnp.float32):
        """List of dense caches, one per layer: (k, v) 2-tuples, or
        quantized (k_i8, v_i8, k_scale, v_scale) 4-tuples for
        ``dtype="int8"`` (see models.generation.make_dense_caches — raw
        unscaled int8 caches must never exist)."""
        from ...models.generation import make_dense_caches
        return make_dense_caches(self.num_layers, batch, max_len,
                                 self.num_kv_heads, self.head_dim, dtype)

    def quantize_weights(self, algo="weight_only_int8", group_size=-1):
        """Serving-time weight-only quantization of every projection in
        the stack (reference: the FusedMultiTransformer kernel's
        weight_only int8/int4 mode over the Cutlass fpA_intB GEMM).
        Returns the number of Linears swapped."""
        from ...nn.quant import quantize_linears
        return quantize_linears(self, algo=algo, group_size=group_size)

    def _split_qkv(self, qkv, b, s):
        h, hkv, d = self.num_heads, self.num_kv_heads, self.head_dim
        q, k, v = jnp.split(qkv, [h * d, h * d + hkv * d], axis=-1)
        return (q.reshape(b, s, h, d), k.reshape(b, s, hkv, d),
                v.reshape(b, s, hkv, d))

    def _ffn(self, x, blk):
        h = blk.ffn1(x)
        if self.activation == "swiglu":
            h = F.swiglu(h)
        else:
            h = F.gelu(h)
        return blk.ffn2(h)

    def forward(self, x, caches=None, seq_lens=None, position_offset=0):
        """Prefill: x (B, S, E), caches filled in [0, S). Decode: x (B, 1, E)
        with seq_lens (B,) = positions to write. Returns (out, new_caches)."""
        b, s, _ = x.shape
        decode = caches is not None and s == 1 and seq_lens is not None
        new_caches = []
        cos_sin_len = (int(position_offset) + s) if not decode else None
        for i, blk in enumerate(self._layers):
            residual = x
            h = blk.ln_attn(x)
            q, k, v = self._split_qkv(blk.qkv_proj(h), b, s)
            if decode:
                # rotary at absolute position seq_lens
                cos, sin = F.rope_cos_sin(1, self.head_dim,
                                          base=self.rope_theta,
                                          position_ids=seq_lens[:, None])
                q, k = F.apply_rotary_pos_emb(q, k, cos, sin)
                out, new_cache = decode_attend_cache(
                    caches[i], q[:, 0], k[:, 0], v[:, 0], seq_lens)
                attn = out[:, None]
                new_caches.append(new_cache)
            else:
                cos, sin = F.rope_cos_sin(cos_sin_len, self.head_dim,
                                          base=self.rope_theta)
                cos, sin = cos[position_offset:], sin[position_offset:]
                q, k = F.apply_rotary_pos_emb(q, k, cos, sin)
                if caches is not None:
                    new_caches.append(prefill_write_cache(
                        caches[i], k, v, offset=position_offset))
                if position_offset and caches is not None:
                    # chunked prefill: attend over the cached prefix TOO,
                    # with an offset-causal mask (query i sees keys
                    # < position_offset + i + 1)
                    k, v = read_cache_prefix(
                        new_caches[-1], position_offset + s, q.dtype)
                    mask = (jnp.arange(position_offset + s)[None, :]
                            <= position_offset + jnp.arange(s)[:, None])
                    mask = jnp.where(mask, 0.0, -jnp.inf)[None, None]
                else:
                    mask = None
                rep = self.num_heads // self.num_kv_heads
                kf = jnp.repeat(k, rep, axis=2) if rep > 1 else k
                vf = jnp.repeat(v, rep, axis=2) if rep > 1 else v
                attn = F.scaled_dot_product_attention(
                    q, kf, vf, attn_mask=mask, is_causal=(mask is None))
            attn = attn.reshape(b, s, self.embed_dim)
            x = residual + blk.out_proj(attn)
            x = x + self._ffn(blk.ln_ffn(x), blk)
        return x, (new_caches if caches is not None else None)


class FusedLinear(Layer):
    """Reference: paddle.incubate.nn.FusedLinear (fused matmul+bias).

    On TPU XLA fuses the bias add into the matmul epilogue unaided; the
    class exists for API parity with ported inference code."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        from ...nn import initializer as I
        self.transpose_weight = transpose_weight
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0)))

    def forward(self, x):
        w = self.weight.T if self.transpose_weight else self.weight
        y = x @ w
        return y if self.bias is None else y + self.bias


class FusedMultiHeadAttention(Layer):
    """Reference: paddle.incubate.nn.FusedMultiHeadAttention — pre/post-LN
    self-attention block with residual (fused_attention kernel)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, normalize_before=False,
                 need_weights=False, weight_attr=None, bias_attr=None,
                 epsilon=1e-5, name=None):
        super().__init__()
        from ...nn.layers_common import LayerNorm, Linear
        if need_weights:
            raise ValueError(
                "FusedMultiHeadAttention does not materialize attention "
                "weights (reference asserts need_weights=False too)")
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim,
                               weight_attr=weight_attr, bias_attr=bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim,
                               weight_attr=weight_attr, bias_attr=bias_attr)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, attn_mask=None):
        b, s, e = x.shape
        h = x
        if self.normalize_before:
            h = self.norm(h)
        qkv = self.qkv_proj(h).reshape(b, s, 3, self.num_heads,
                                       self.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, training=self.training)
        out = self.out_proj(attn.reshape(b, s, e))
        out = F.dropout(out, p=self.dropout_rate, training=self.training)
        out = x + out
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    """Reference: paddle.incubate.nn.FusedFeedForward — pre/post-LN MLP
    block with residual (fused_feedforward kernel)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        from ...nn.layers_common import LayerNorm, Linear
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.fc1 = Linear(d_model, dim_feedforward,
                          weight_attr=weight_attr, bias_attr=bias_attr)
        self.fc2 = Linear(dim_feedforward, d_model,
                          weight_attr=weight_attr, bias_attr=bias_attr)
        self.norm = LayerNorm(d_model, epsilon=epsilon)

    def forward(self, x):
        h = x
        if self.normalize_before:
            h = self.norm(h)
        h = getattr(F, self.activation)(self.fc1(h))
        h = F.dropout(h, p=self.act_dropout_rate, training=self.training)
        h = F.dropout(self.fc2(h), p=self.dropout_rate,
                      training=self.training)
        out = x + h
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """Reference: paddle.incubate.nn.FusedTransformerEncoderLayer —
    FusedMultiHeadAttention + FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None):
        return self.ffn(self.fused_attn(src, src_mask))


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
