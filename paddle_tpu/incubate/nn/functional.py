"""``paddle.incubate.nn.functional`` parity — the fused-op surface.

Reference: python/paddle/incubate/nn/functional/ (fused_rms_norm,
fused_layer_norm, fused_bias_act, fused_dropout_add, fused_linear,
fused_rotary_position_embedding, masked_multihead_attention,
variable_length_memory_efficient_attention) backed by
paddle/phi/kernels/fusion/gpu/ CUDA kernels.

TPU redesign: "fused" is what XLA does by default — these entry points keep
the reference call signatures and lower to jnp compositions XLA fuses into
single kernels (elementwise chains fuse into the preceding matmul/reduce).
The decode-attention ops (masked_multihead_attention, paged_attention) are
the genuinely structural ones: they implement single-token KV-cache
attention, the TPU analogue of the reference's decode kernels.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...nn import functional as F

# direct re-exports where the base framework already has the op
fused_rotary_position_embedding = F.fused_rotary_position_embedding
flash_attention = F.flash_attention
scaled_dot_product_attention = F.scaled_dot_product_attention


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, residual=None):
    """rms_norm(+optional residual add) — reference RmsNormKernel.
    ``begin_norm_axis``: normalize over axes [begin_norm_axis, ndim)."""
    if residual is not None:
        x = x + residual
    if begin_norm_axis in (-1, x.ndim - 1):
        out = F.rms_norm(x, norm_weight, epsilon)
    else:
        axes = tuple(range(begin_norm_axis % x.ndim, x.ndim))
        ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes,
                      keepdims=True)
        out = (x * jax.lax.rsqrt(ms + epsilon)).astype(x.dtype)
        if norm_weight is not None:
            out = out * norm_weight
    if norm_bias is not None:
        out = out + norm_bias
    return (out, x) if residual is not None else out


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     residual=None):
    if residual is not None:
        x = x + residual
    out = F.layer_norm(x, weight=norm_weight, bias=norm_bias,
                       epsilon=epsilon)
    return (out, x) if residual is not None else out


def fused_linear(x, weight, bias=None, transpose_weight=False):
    w = weight.T if transpose_weight else weight
    return F.linear(x, w, bias)


def fused_bias_act(x, bias=None, act_method="gelu"):
    if bias is not None:
        x = x + bias
    def _geglu(v):
        a, g = jnp.split(v, 2, axis=-1)
        return a * F.gelu(g)

    acts = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu,
            "swiglu": F.swiglu, "geglu": _geglu}
    return acts[act_method](x)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train"):
    return F.dropout(x, p, training=training, mode=mode) + y


def swiglu(x, y=None):
    return F.swiglu(x, y)


# ---------------------------------------------------------------------------
# fused-kernel library entry points (docs/KERNELS.md)
#
# Each op dispatches to its Pallas kernel on TPU (ops/pallas) and
# otherwise runs the XLA composition below — the composition IS the
# kernel's numerical contract (same op order, f32 accumulation), so the
# interpret-mode equivalence tests in tests/test_fused_kernels.py pin
# the two together.  Backward passes recompute through the composition
# (jax.vjp over the reference), the flash-attention remat recipe: the
# fused forward saves the HBM traffic, the backward pays one extra
# forward in exchange for standard XLA gradients.
# ---------------------------------------------------------------------------

def _prec(dtype):
    # HIGHEST only where it means something: the TPU MXU truncates f32
    # operands to bf16 by default (the int4_matmul note).  On CPU the
    # default f32 dot is already exact and HIGHEST picks a measurably
    # slower codegen path (autotune sweep, 2026-08-04: 57 → 37 ms on the
    # 350m MLP shape).
    return (jax.lax.Precision.HIGHEST
            if dtype == jnp.float32 and jax.default_backend() == "tpu"
            else None)


def _fused_swiglu_mlp_ref(x, w_gate, w_up, w_down):
    """XLA composition mirroring the fused_mlp kernel's numerics."""
    p = _prec(x.dtype)
    g = jax.lax.dot(x, w_gate.astype(x.dtype), precision=p,
                    preferred_element_type=jnp.float32)
    u = jax.lax.dot(x, w_up.astype(x.dtype), precision=p,
                    preferred_element_type=jnp.float32)
    h = (jax.nn.silu(g) * u).astype(x.dtype)
    return jax.lax.dot(h, w_down.astype(x.dtype), precision=p,
                       preferred_element_type=jnp.float32).astype(x.dtype)


def _fused_swiglu_mlp_impl(x, w_gate, w_up, w_down):
    from ...ops import dispatch as _dispatch
    kernel = _dispatch.get("fused_swiglu_mlp")
    if kernel is not None:
        out = kernel(x, w_gate.astype(x.dtype), w_up.astype(x.dtype),
                     w_down.astype(x.dtype))
        if out is not None:
            return out
    return _fused_swiglu_mlp_ref(x, w_gate, w_up, w_down)


@jax.custom_vjp
def fused_swiglu_mlp(x, w_gate, w_up, w_down):
    """``silu(x @ Wg) · (x @ Wu) @ Wd`` in one pass — the (T, I) gate/up
    intermediate never round-trips HBM on TPU (ops/pallas/fused_mlp.py);
    XLA composition elsewhere.  x: (T, H); returns (T, H) in x.dtype."""
    return _fused_swiglu_mlp_impl(x, w_gate, w_up, w_down)


def _fused_swiglu_mlp_fwd(x, w_gate, w_up, w_down):
    return _fused_swiglu_mlp_impl(x, w_gate, w_up, w_down), \
        (x, w_gate, w_up, w_down)


def _fused_swiglu_mlp_bwd(res, ct):
    _, vjp = jax.vjp(_fused_swiglu_mlp_ref, *res)
    return vjp(ct)


fused_swiglu_mlp.defvjp(_fused_swiglu_mlp_fwd, _fused_swiglu_mlp_bwd)


def _fused_gelu_mlp_ref(x, w1, b1, w2, b2):
    p = _prec(x.dtype)
    h1 = jax.lax.dot(x, w1.astype(x.dtype), precision=p,
                     preferred_element_type=jnp.float32)
    h1 = h1 + b1.astype(jnp.float32)
    h = jax.nn.gelu(h1, approximate=False).astype(x.dtype)
    y = jax.lax.dot(h, w2.astype(x.dtype), precision=p,
                    preferred_element_type=jnp.float32)
    return (y + b2.astype(jnp.float32)).astype(x.dtype)


def _fused_gelu_mlp_impl(x, w1, b1, w2, b2):
    from ...ops import dispatch as _dispatch
    kernel = _dispatch.get("fused_gelu_mlp")
    if kernel is not None:
        out = kernel(x, w1.astype(x.dtype), b1, w2.astype(x.dtype), b2)
        if out is not None:
            return out
    return _fused_gelu_mlp_ref(x, w1, b1, w2, b2)


@jax.custom_vjp
def fused_gelu_mlp(x, w1, b1, w2, b2):
    """``gelu(x @ W1 + b1) @ W2 + b2`` in one pass (the GPT 4h FFN
    analogue of :func:`fused_swiglu_mlp`)."""
    return _fused_gelu_mlp_impl(x, w1, b1, w2, b2)


def _fused_gelu_mlp_fwd(x, w1, b1, w2, b2):
    return _fused_gelu_mlp_impl(x, w1, b1, w2, b2), (x, w1, b1, w2, b2)


def _fused_gelu_mlp_bwd(res, ct):
    _, vjp = jax.vjp(_fused_gelu_mlp_ref, *res)
    return vjp(ct)


fused_gelu_mlp.defvjp(_fused_gelu_mlp_fwd, _fused_gelu_mlp_bwd)


def _fused_rms_rope_qkv_ref(x, norm_weight, w_q, w_k, w_v, cos, sin,
                            head_dim, eps):
    """XLA composition mirroring the fused_norm_qkv kernel: rms-norm in
    f32, projections with f32 accumulation, rotate-half rope in f32.
    The kernel's selector-matmul rotation is exact (±1 entries), so the
    concat formulation here is the same arithmetic."""
    p = _prec(x.dtype)
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    nx = (xf * jax.lax.rsqrt(ms + eps)
          * norm_weight.astype(jnp.float32)).astype(x.dtype)

    def proj(w):
        return jax.lax.dot(nx, w.astype(x.dtype), precision=p,
                           preferred_element_type=jnp.float32)

    def rope(y):
        # rope runs on the x.dtype-ROUNDED projection (mirroring both
        # the kernel and the unfused path, where the projection layer's
        # output dtype is what the rotary pass sees), products in f32
        t, n = y.shape
        yh = y.astype(x.dtype).astype(jnp.float32) \
            .reshape(t, n // head_dim, head_dim)
        half = head_dim // 2
        rot = jnp.concatenate([-yh[..., half:], yh[..., :half]], axis=-1)
        c = cos.astype(jnp.float32)[:, None, :]
        s = sin.astype(jnp.float32)[:, None, :]
        return (yh * c + rot * s).reshape(t, n)

    q = proj(w_q)
    k = proj(w_k)
    return (rope(q).astype(x.dtype), rope(k).astype(x.dtype),
            proj(w_v).astype(x.dtype))


def _fused_rms_rope_qkv_impl(x, norm_weight, w_q, w_k, w_v, cos, sin,
                             head_dim, eps):
    from ...ops import dispatch as _dispatch
    kernel = _dispatch.get("fused_rms_rope_qkv")
    if kernel is not None:
        out = kernel(x, norm_weight, w_q.astype(x.dtype),
                     w_k.astype(x.dtype), w_v.astype(x.dtype), cos, sin,
                     head_dim, eps)
        if out is not None:
            return out
    return _fused_rms_rope_qkv_ref(x, norm_weight, w_q, w_k, w_v, cos,
                                   sin, head_dim, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def fused_rms_rope_qkv(x, norm_weight, w_q, w_k, w_v, cos, sin,
                       head_dim, eps=1e-5):
    """rms_norm → q/k/v projections → rotate-half rope on q/k in ONE
    pass over the hidden states (ops/pallas/fused_norm_qkv.py on TPU;
    XLA composition elsewhere).

    x: (T, H) flattened hidden states; norm_weight: (H,); w_q: (H, Nq);
    w_k/w_v: (H, Nk); cos/sin: (T, head_dim).  Returns ``(q, k, v)``
    with rope already applied to q and k, in ``x.dtype``.
    """
    return _fused_rms_rope_qkv_impl(x, norm_weight, w_q, w_k, w_v, cos,
                                    sin, head_dim, eps)


def _fused_rms_rope_qkv_fwd(x, norm_weight, w_q, w_k, w_v, cos, sin,
                            head_dim, eps):
    out = _fused_rms_rope_qkv_impl(x, norm_weight, w_q, w_k, w_v, cos,
                                   sin, head_dim, eps)
    return out, (x, norm_weight, w_q, w_k, w_v, cos, sin)


def _fused_rms_rope_qkv_bwd(head_dim, eps, res, ct):
    _, vjp = jax.vjp(
        lambda *a: _fused_rms_rope_qkv_ref(*a, head_dim, eps), *res)
    return vjp(ct)


fused_rms_rope_qkv.defvjp(_fused_rms_rope_qkv_fwd,
                          _fused_rms_rope_qkv_bwd)


def _lora_bgmv_ref(x, a, b, idx):
    """XLA composition mirroring the grouped-BGMV kernel's numerics
    (ops/pallas/lora_matmul.py): gather each slot's adapter blocks,
    shrink then expand with f32 accumulation, the rank-r intermediate
    rounded to ``x.dtype`` between the two dots.  Slot 0 rows multiply
    all-zero stacks, so their delta is EXACTLY 0.0 — adding it leaves
    base-only outputs bitwise unchanged."""
    p = _prec(x.dtype)
    ai = jnp.take(a, idx, axis=0).astype(x.dtype)      # (B, d_in, r)
    bi = jnp.take(b, idx, axis=0).astype(x.dtype)      # (B, r, d_out)
    h = jax.lax.dot_general(x, ai, (((2,), (1,)), ((0,), (0,))),
                            precision=p,
                            preferred_element_type=jnp.float32)
    h = h.astype(x.dtype)                              # (B, C, r)
    out = jax.lax.dot_general(h, bi, (((2,), (1,)), ((0,), (0,))),
                              precision=p,
                              preferred_element_type=jnp.float32)
    return out.astype(x.dtype)


def lora_bgmv(x, a, b, idx):
    """Grouped batched-gather matrix-vector product — the multi-LoRA
    serving delta ``x[s] @ A[idx[s]] @ B[idx[s]]`` per batch slot
    (docs/SERVING.md "Multi-LoRA").

    ``x`` is ``(B, C, d_in)`` (the projection's input span batch),
    ``a``/``b`` the stacked adapter pools ``(N, d_in, r)`` /
    ``(N, r, d_out)`` (``serving.LoRAPool.device_stacks``), ``idx``
    the per-slot adapter indices ``(B,)`` int32.  Mixed indices within
    one batch are the point; index 0 is the reserved exact no-op.
    Dispatches to the Pallas grouped-BGMV kernel on TPU (adapter blocks
    DMA'd by scalar-prefetched index, rank-r intermediate
    VMEM-resident); the gather+einsum composition above is the
    numerical contract and the fallback everywhere else.  Serving-only:
    no custom VJP (LoRA *training* is out of scope — deltas are jit
    inputs, not trained parameters here)."""
    from ...ops import dispatch as _dispatch
    kernel = _dispatch.get("lora_bgmv")
    if kernel is not None:
        out = kernel(x, a, b, idx)
        if out is not None:
            return out
    return _lora_bgmv_ref(x, a, b, idx)


def lora_delta(lora, inp, key):
    """The one adapter-delta call the model forwards share: resolve
    projection ``key`` in the threaded ``(layer pack, adapter ids)``
    pair and run :func:`lora_bgmv` on its stacks — ``None`` when no
    pack is threaded or the pool does not target this projection (the
    caller then skips the add outright)."""
    if lora is None:
        return None
    lpack, laids = lora
    e = lpack.get(key)
    if e is None:
        return None
    return lora_bgmv(inp, e["a"], e["b"], laids)


# ---------------------------------------------------------------------------
# decode attention (KV cache)
# ---------------------------------------------------------------------------

def quantize_kv(x):
    """THE int8 KV quantizer (symmetric, per-(…, head) over the last dim):
    returns (int8 values, f32 scales).  Shared by the decode write below,
    the model families' prefill writes, and the tests — one formula to
    change."""
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=-1) / 127.0 + 1e-12
    return jnp.round(xf / s[..., None]).astype(jnp.int8), s


def prefill_write_cache(cache, k, v, offset=0):
    """Write a prefill chunk at positions [offset, offset+s) into a dense
    cache tuple — 2-tuple fp or 4-tuple int8-quantized (see
    make_dense_caches)."""
    upd = jax.lax.dynamic_update_slice_in_dim
    if len(cache) == 4:
        kc, vc, ks, vs = cache
        k_q, ks_new = quantize_kv(k)
        v_q, vs_new = quantize_kv(v)
        return (upd(kc, k_q, offset, axis=1), upd(vc, v_q, offset, axis=1),
                upd(ks, ks_new, offset, axis=1),
                upd(vs, vs_new, offset, axis=1))
    kc, vc = cache
    return (upd(kc, k.astype(kc.dtype), offset, axis=1),
            upd(vc, v.astype(vc.dtype), offset, axis=1))


def read_cache_prefix(cache, length, dtype):
    """Read positions [0, length) from a dense cache tuple as ``dtype``
    K/V — dequantizing through the per-(position, head) scales for the
    int8 4-tuple layout.  Used by chunked prefill to attend over the
    already-cached prefix."""
    if len(cache) == 4:
        kc, vc, ks, vs = cache
        k = kc[:, :length].astype(dtype) * ks[:, :length, :, None].astype(dtype)
        v = vc[:, :length].astype(dtype) * vs[:, :length, :, None].astype(dtype)
        return k, v
    kc, vc = cache
    return kc[:, :length].astype(dtype), vc[:, :length].astype(dtype)


def decode_attend_cache(cache, q, new_k, new_v, seq_lens):
    """One decode step against a dense cache tuple — 2-tuple fp or
    4-tuple int8-quantized.  The single cache-arity dispatch shared by
    the model families.  Returns (out, new_cache)."""
    if len(cache) == 4:
        kc, vc, ks, vs = cache
        out, kc, vc, ks, vs = masked_multihead_attention(
            q, kc, vc, seq_lens, new_k, new_v, k_scale=ks, v_scale=vs)
        return out, (kc, vc, ks, vs)
    kc, vc = cache
    out, kc, vc = masked_multihead_attention(q, kc, vc, seq_lens,
                                             new_k, new_v)
    return out, (kc, vc)


def masked_multihead_attention(q, k_cache, v_cache, seq_lens,
                               new_k=None, new_v=None, scale=None,
                               k_scale=None, v_scale=None,
                               uniform_lens=False):
    """Single-step decode attention against a dense KV cache.

    Reference: MaskedMultiheadAttentionKernel
    (paddle/phi/kernels/fusion/gpu/, SURVEY §2.1 fused kernels row; the
    reference kernel also carries the int8 cache_kv_quant path).

    q:        (B, H, D)        — the new token's query
    k_cache:  (B, S_max, H_kv, D) — updated IN-PLACE-style: returns new cache
    seq_lens: (B,)             — current lengths (position of the new token)
    new_k/new_v: (B, H_kv, D)  — this step's k/v, written at seq_lens
    k_scale/v_scale: (B, S_max, H_kv) f32 — present iff the caches are
    int8-quantized (per-position, per-head symmetric scales).  Decode is
    HBM-bandwidth-bound, so int8 caches halve the dominant traffic; the
    dequant multiply fuses into the einsum operand load.

    Returns (out, k_cache, v_cache) — plus the updated scales when
    quantized: (out, k_cache, v_cache, k_scale, v_scale).
    """
    b, h, d = q.shape
    s_max = k_cache.shape[1]
    h_kv = k_cache.shape[2]
    quantized = k_scale is not None
    if new_k is not None:
        # One-token cache write.  Measured on-chip (v5e, bs8 decode,
        # docs/BENCH.md): the "where" full-cache rewrite STREAMS at HBM
        # bandwidth and beats both indexed alternatives —
        # dynamic_update_slice at a traced start (4.0/7.6 ms bf16/int8 per
        # step: the traced index defeats in-place aliasing inside the scan,
        # so XLA copies the cache) and per-row scatter (3.5/5.7 ms) vs
        # where at 3.0/1.4-2.7 ms.  PDTPU_MMA_WRITE=where|slice|scatter
        # keeps the experiment reproducible.
        if quantized:
            k_q, ks_new = quantize_kv(new_k)
            v_q, vs_new = quantize_kv(new_v)
            writes = [("k", k_q), ("v", v_q),
                      ("ks", ks_new), ("vs", vs_new)]
        else:
            # cast to the cache dtype: mixing dtypes here would silently
            # promote the whole cache (and break scan carries holding it)
            writes = [("k", new_k.astype(k_cache.dtype)),
                      ("v", new_v.astype(v_cache.dtype))]
        import os as _os
        strategy = _os.environ.get("PDTPU_MMA_WRITE", "where")
        if strategy not in ("where", "slice", "scatter"):
            raise ValueError(
                f"PDTPU_MMA_WRITE={strategy!r}: expected "
                "where|slice|scatter")
        # slice writes ONE slab at seq_lens[0]: only valid when every
        # row's length advances in lockstep.  Callers that KNOW this pass
        # uniform_lens=True; PDTPU_MMA_UNIFORM=1 is the operator's
        # equivalent assertion for the generate() A/B (the model families
        # cannot see whether their caller is the lockstep decode loop).
        if strategy == "slice":
            uniform_lens = (uniform_lens or
                            _os.environ.get("PDTPU_MMA_UNIFORM") == "1")
            if not uniform_lens:
                raise ValueError(
                    "PDTPU_MMA_WRITE=slice requires lockstep lens: pass "
                    "uniform_lens=True (op callers) or set "
                    "PDTPU_MMA_UNIFORM=1 (generate() benchmarking) — "
                    "ragged lens would be silently corrupted")
        caches = {"k": k_cache, "v": v_cache, "ks": k_scale, "vs": v_scale}
        for name, val in writes:
            if strategy == "slice":
                caches[name] = jax.lax.dynamic_update_slice_in_dim(
                    caches[name], val[:, None], seq_lens[0], axis=1)
            elif strategy == "where":
                onemask = (jnp.arange(s_max)[None, :] ==
                           seq_lens[:, None])
                shaped = onemask[(...,) + (None,) * (val.ndim - 1)]
                caches[name] = jnp.where(shaped, val[:, None], caches[name])
            else:
                caches[name] = caches[name].at[
                    jnp.arange(q.shape[0]), seq_lens].set(val, mode="drop")
        k_cache, v_cache = caches["k"], caches["v"]
        k_scale, v_scale = caches["ks"], caches["vs"]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    g = h // h_kv
    if quantized:
        k_read = k_cache.astype(jnp.bfloat16) * \
            k_scale.astype(jnp.bfloat16)[..., None]
        v_read = v_cache.astype(jnp.float32) * v_scale[..., None]
    else:
        k_read, v_read = k_cache, v_cache
    # GQA without materializing repeated KV: group the q heads per kv head
    # and contract against the kv head axis directly (4x less HBM traffic
    # at 4-way GQA); accumulate in fp32 on the MXU
    qg = q.reshape(b, h_kv, g, d)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k_read,
                        preferred_element_type=jnp.float32) * scale
    mask = jnp.arange(s_max)[None, None, None, :] <= \
        seq_lens[:, None, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    # probs stay fp32 through the PV contraction (decode is bandwidth-bound;
    # bf16-rounding the probabilities would cost accuracy for nothing)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v_read,
                     preferred_element_type=jnp.float32)
    out = out.reshape(b, h, d).astype(q.dtype)
    if quantized:
        return out, k_cache, v_cache, k_scale, v_scale
    return out, k_cache, v_cache


def paged_attention(q, k_cache, v_cache, block_tables, context_lens,
                    scale: Optional[float] = None):
    """Decode attention over a PAGED (block) KV cache — vLLM-style serving.

    Reference capability: paged/block attention in the reference serving
    stack (PaddleNLP inference; core provides the fused decode kernels).

    q:            (B, H, D)
    k_cache/v_cache: (num_blocks, block_size, H_kv, D) — global block pool
    block_tables: (B, max_blocks_per_seq) int32 — per-seq block ids
    context_lens: (B,) — tokens so far (incl. current)

    On TPU this dispatches to the Pallas kernel
    (ops/pallas/decode_attention.py) whose scalar-prefetched block table
    DMAs each page straight from the pool — the XLA gather below
    materializes the gathered cache and is orders of magnitude slower
    on TPU; it remains the CPU/fallback reference implementation.
    """
    from ...ops import dispatch as _dispatch
    kernel = _dispatch.get("paged_attention")
    if kernel is not None:
        out = kernel(q, k_cache, v_cache, block_tables, context_lens,
                     scale=scale)
        if out is not None:
            return out
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    k, v = _paged_gather_dense(k_cache, v_cache, block_tables)
    return _attend_dense_gqa(q, k, v, context_lens, scale)


def write_paged_kv(k_cache, v_cache, new_k, new_v, block_tables,
                   context_lens) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter this step's (B, H_kv, D) k/v into the paged pool at position
    context_lens-1 of each sequence."""
    b = new_k.shape[0]
    bs = k_cache.shape[1]
    pos = context_lens - 1
    blk = jnp.take_along_axis(block_tables, (pos // bs)[:, None],
                              axis=1)[:, 0]
    off = pos % bs
    k_cache = k_cache.at[blk, off].set(new_k)
    v_cache = v_cache.at[blk, off].set(new_v)
    return k_cache, v_cache


def _paged_gather_dense(k_cache, v_cache, block_tables, k_scale=None,
                        v_scale=None):
    """Gather a batch's pages from the pool into dense (B, S, H_kv, D)
    fp32 K/V — dequantizing through the per-(position, head) scales for
    int8 pools.  Only the gathered blocks materialize, never the pool."""
    nb, bs, h_kv, d = k_cache.shape
    b, mb = block_tables.shape
    k = k_cache[block_tables].reshape(b, mb * bs, h_kv, d)
    v = v_cache[block_tables].reshape(b, mb * bs, h_kv, d)
    if k_scale is not None:
        k = k.astype(jnp.float32) * \
            k_scale[block_tables].reshape(b, mb * bs, h_kv)[..., None]
        v = v.astype(jnp.float32) * \
            v_scale[block_tables].reshape(b, mb * bs, h_kv)[..., None]
    return k, v


def _attend_dense_gqa(q, k, v, context_lens, scale):
    """Masked decode attention over dense (B, S, H_kv, D) K/V without
    repeating KV across the GQA groups (shared by the paged fallbacks)."""
    b, h, d = q.shape
    s = k.shape[1]
    h_kv = k.shape[2]
    g = h // h_kv
    qg = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    mask = jnp.arange(s)[None, None, None, :] < \
        context_lens[:, None, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(b, h, d).astype(q.dtype)


def paged_decode_attend(cache, q, new_k, new_v, block_tables, write_pos,
                        scale: Optional[float] = None):
    """One decode step against PAGED pools — the serving analogue of
    :func:`decode_attend_cache`, sharing its cache-arity dispatch.

    ``cache`` is the per-layer pool tuple: fp ``(k, v)`` with shape
    ``(num_blocks, page, H_kv, D)``, or int8-quantized
    ``(k_i8, v_i8, k_scale, v_scale)`` with ``(num_blocks, page, H_kv)``
    f32 scales (the :func:`quantize_kv` formula, same as the dense
    4-tuple caches).  ``write_pos`` (B,) is the new token's position —
    i.e. the number of tokens already cached; the step writes this
    token's ``(B, H_kv, D)`` k/v at that position and attends over
    ``write_pos + 1`` tokens.

    A slot whose block-table entries are out of range (the serving
    scheduler's inactive-slot sentinel) drops its write (out-of-bounds
    scatter) and produces a garbage-but-finite output the caller
    discards — nothing a dead slot does can corrupt live blocks.

    Returns ``(out, new_cache)``.
    """
    bs = cache[0].shape[1]
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    ctx = write_pos + 1
    if len(cache) == 4:
        kc, vc, ks, vs = cache
        blk = jnp.take_along_axis(block_tables, (write_pos // bs)[:, None],
                                  axis=1)[:, 0]
        off = write_pos % bs
        k_q, ks_new = quantize_kv(new_k)
        v_q, vs_new = quantize_kv(new_v)
        kc = kc.at[blk, off].set(k_q)
        vc = vc.at[blk, off].set(v_q)
        ks = ks.at[blk, off].set(ks_new)
        vs = vs.at[blk, off].set(vs_new)
        # int8 pools attend through the XLA gather+dequant formulation on
        # every backend: the Pallas kernel is fp-only, and int8 halves
        # the gathered bytes, which is the traffic that matters
        kd, vd = _paged_gather_dense(kc, vc, block_tables, ks, vs)
        out = _attend_dense_gqa(q, kd, vd, ctx, scale)
        return out, (kc, vc, ks, vs)
    kc, vc = cache
    kc, vc = write_paged_kv(kc, vc, new_k.astype(kc.dtype),
                            new_v.astype(vc.dtype), block_tables, ctx)
    out = paged_attention(q, kc, vc, block_tables, ctx, scale=scale)
    return out, (kc, vc)


def _paged_span_write(cache, k, v, block_tables, span_starts, span_lens):
    """Scatter a token span ``k``/``v`` (B, C, H_kv, D) into the paged
    pools at positions ``[span_starts, span_starts + span_lens)`` of each
    sequence.  Rows ``>= span_lens`` (chunk padding, idle slots) get an
    out-of-range block id and are DROPPED by the scatter, so padding
    never lands in the pool.  Shared cache-arity dispatch (fp 2-tuple or
    int8 4-tuple)."""
    b, s = k.shape[:2]
    nb, bs = cache[0].shape[:2]
    mb = block_tables.shape[1]
    pos = span_starts[:, None] + jnp.arange(s)[None, :]       # (B, C)
    blk = jnp.take_along_axis(block_tables, jnp.minimum(pos // bs, mb - 1),
                              axis=1)
    live = jnp.arange(s)[None, :] < span_lens[:, None]
    blk = jnp.where(live, blk, nb)                            # OOB → dropped
    off = pos % bs
    if len(cache) == 4:
        kc, vc, ks, vs = cache
        k_q, ks_new = quantize_kv(k)
        v_q, vs_new = quantize_kv(v)
        return (kc.at[blk, off].set(k_q), vc.at[blk, off].set(v_q),
                ks.at[blk, off].set(ks_new), vs.at[blk, off].set(vs_new))
    kc, vc = cache
    return (kc.at[blk, off].set(k.astype(kc.dtype)),
            vc.at[blk, off].set(v.astype(vc.dtype)))


def paged_prefill_write(cache, k, v, block_tables, prompt_lens):
    """Scatter a prefill chunk ``k``/``v`` (B, S, H_kv, D) into the paged
    pools at positions ``[0, prompt_lens)`` of each sequence — the
    span write with every span starting at position 0 (the legacy
    bucket-prefill path; the ragged serving step uses
    :func:`ragged_paged_attend`)."""
    b = k.shape[0]
    return _paged_span_write(cache, k, v, block_tables,
                             jnp.zeros((b,), jnp.int32), prompt_lens)


def _ragged_attend_dense(q, k, v, span_starts, scale):
    """Span attention over dense gathered (B, S, H_kv, D) K/V: query row
    ``j`` of slot ``b`` (position ``span_starts[b] + j``) attends over
    positions ``[0, span_starts[b] + j]``.  GQA without repeating KV,
    fp32 accumulation — the (B, C)-shaped analogue of
    :func:`_attend_dense_gqa` (shared by the ragged fallbacks)."""
    b, c, h, d = q.shape
    s = k.shape[1]
    h_kv = k.shape[2]
    g = h // h_kv
    qg = q.reshape(b, c, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bckgd,bskd->bckgs", qg, k.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    pos = span_starts[:, None] + jnp.arange(c)[None, :]       # (B, C)
    # position 0 is always visible (pos >= 0), so no row softmaxes over
    # an empty set — dead rows produce finite garbage the caller discards
    mask = jnp.arange(s)[None, None, :] <= pos[:, :, None]    # (B, C, S)
    scores = jnp.where(mask[:, :, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bckgs,bskd->bckgd", probs, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(b, c, h, d).astype(q.dtype)


def ragged_paged_attend(cache, q, new_k, new_v, block_tables, span_starts,
                        span_lens, scale: Optional[float] = None):
    """ONE serving step for a ragged batch of token spans — the unified
    replacement for the separate :func:`paged_decode_attend` /
    bucket-prefill dispatches (PAPERS.md "Ragged Paged Attention").

    Each slot ``b`` carries a span of ``span_lens[b]`` tokens starting at
    pool position ``span_starts[b]``: a chunked-prefill segment
    (``len > 1``), a single decode token (``len == 1``), or nothing
    (``len == 0`` — idle or dead slot; with an out-of-range block table
    its writes drop and its garbage output is discarded, so nothing a
    dead slot does can corrupt live blocks).

    ``q``/``new_k``/``new_v`` are ``(B, C, H|H_kv, D)``; the span's k/v
    is written at ``[start, start + len)`` and query row ``j`` attends
    over pool positions ``[0, start + j]`` — the cached prefix plus the
    causal part of its own span.  ``cache`` is the per-layer pool tuple
    (fp 2-tuple or int8 4-tuple with :func:`quantize_kv` scales); int8
    pools attend through the XLA gather+dequant formulation on every
    backend (the Pallas kernel is fp-only), fp pools dispatch to the
    ragged Pallas kernel on TPU.

    Returns ``(out (B, C, H, D), new_cache)``.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    new_cache = _paged_span_write(cache, new_k, new_v, block_tables,
                                  span_starts, span_lens)
    if len(new_cache) == 4:
        kc, vc, ks, vs = new_cache
        kd, vd = _paged_gather_dense(kc, vc, block_tables, ks, vs)
        return (_ragged_attend_dense(q, kd, vd, span_starts, scale),
                new_cache)
    kc, vc = new_cache
    from ...ops import dispatch as _dispatch
    kernel = _dispatch.get("ragged_paged_attention")
    if kernel is not None:
        out = kernel(q, kc, vc, block_tables, span_starts, span_lens,
                     scale=scale)
        if out is not None:
            return out, new_cache
    kd, vd = _paged_gather_dense(kc, vc, block_tables)
    return _ragged_attend_dense(q, kd, vd, span_starts, scale), new_cache


def _mega_decode_layer_ref(x, norm_weight, w_q, w_k, w_v, w_o, cos, sin,
                           cache, block_tables, span_starts, span_lens,
                           head_dim, eps, scale):
    """THE megakernel's numerical contract: the existing fused
    entry points chained — :func:`fused_rms_rope_qkv` →
    :func:`ragged_paged_attend` → O-proj (f32 accumulation, x.dtype
    rounding exactly where the kernel rounds) → residual add.  This is
    what runs on CPU, under meshes, for int8 KV pools (whose
    gather+dequant lives inside :func:`ragged_paged_attend`), and
    wherever ``mega_decode.supported()`` declines; the interpret-mode
    equivalence tests (tests/test_mega_decode.py) pin the Pallas kernel
    to it."""
    b, c, h = x.shape
    q, k, v = fused_rms_rope_qkv(
        x.reshape(b * c, h), norm_weight, w_q, w_k, w_v,
        cos.reshape(b * c, head_dim), sin.reshape(b * c, head_dim),
        head_dim, eps)
    nh = q.shape[-1] // head_dim
    nkh = k.shape[-1] // head_dim
    attn, new_cache = ragged_paged_attend(
        cache, q.reshape(b, c, nh, head_dim),
        k.reshape(b, c, nkh, head_dim), v.reshape(b, c, nkh, head_dim),
        block_tables, span_starts, span_lens, scale=scale)
    p = _prec(x.dtype)
    y = jax.lax.dot(attn.reshape(b * c, nh * head_dim),
                    w_o.astype(x.dtype), precision=p,
                    preferred_element_type=jnp.float32)
    return x + y.astype(x.dtype).reshape(b, c, h), new_cache


def _mega_decode_layer_impl(x, norm_weight, w_q, w_k, w_v, w_o, cos, sin,
                            cache, block_tables, span_starts, span_lens,
                            head_dim, eps, scale):
    from ...ops import dispatch as _dispatch
    kernel = _dispatch.get("mega_decode_layer")
    if kernel is not None and len(cache) == 2:
        xd = x.dtype
        res = kernel(x, norm_weight, w_q.astype(xd), w_k.astype(xd),
                     w_v.astype(xd), w_o.astype(xd), cos, sin,
                     cache[0], cache[1], block_tables, span_starts,
                     span_lens, head_dim, eps, scale=scale)
        if res is not None:
            out, k_new, v_new = res
            # the pool scatter stays the ONE shared _paged_span_write
            # (same OOB dead-slot drop, same dtype rounding) — the
            # kernel only computes the span k/v, it never touches the
            # pools' write path
            nkh = k_new.shape[-1] // head_dim
            b, c = k_new.shape[:2]
            new_cache = _paged_span_write(
                cache, k_new.reshape(b, c, nkh, head_dim),
                v_new.reshape(b, c, nkh, head_dim), block_tables,
                span_starts, span_lens)
            return out, new_cache
    return _mega_decode_layer_ref(x, norm_weight, w_q, w_k, w_v, w_o,
                                  cos, sin, cache, block_tables,
                                  span_starts, span_lens, head_dim, eps,
                                  scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(12, 13, 14))
def mega_decode_layer(x, norm_weight, w_q, w_k, w_v, w_o, cos, sin,
                      cache, block_tables, span_starts, span_lens,
                      head_dim, eps=1e-5, scale=None):
    """One decoder layer's whole ragged attention block —
    rms_norm → q/k/v projections → rotate-half rope → ragged paged
    attention (span write included) → O-proj → residual — as ONE entry
    point, dispatching to the decode megakernel on TPU
    (ops/pallas/mega_decode.py: one Pallas dispatch per layer,
    activations VMEM-resident between stages) and running the pinned
    XLA composition everywhere else.

    x: (B, C, H) residual-stream span batch (UN-normed; the rms-norm
    happens inside); norm_weight: (H,); w_q: (H, Nq); w_k/w_v: (H, Nk);
    w_o: (Nq, H); cos/sin: (B, C, head_dim) per-slot rope tables;
    ``cache``/``block_tables``/``span_starts``/``span_lens`` exactly as
    :func:`ragged_paged_attend`.  Returns ``(x + o_proj(attend),
    new_cache)``.  The custom VJP recomputes through the composition
    (the library's remat recipe) — and makes the whole block ONE closed
    call in the traced step, which is what the Engine's
    ``dispatches_per_step`` gauge counts.
    """
    return _mega_decode_layer_impl(x, norm_weight, w_q, w_k, w_v, w_o,
                                   cos, sin, cache, block_tables,
                                   span_starts, span_lens, head_dim, eps,
                                   scale)


def _mega_decode_layer_fwd(x, norm_weight, w_q, w_k, w_v, w_o, cos, sin,
                           cache, block_tables, span_starts, span_lens,
                           head_dim, eps, scale):
    out = _mega_decode_layer_impl(x, norm_weight, w_q, w_k, w_v, w_o,
                                  cos, sin, cache, block_tables,
                                  span_starts, span_lens, head_dim, eps,
                                  scale)
    return out, (x, norm_weight, w_q, w_k, w_v, w_o, cos, sin, cache,
                 block_tables, span_starts, span_lens)


def _mega_decode_layer_bwd(head_dim, eps, scale, res, ct):
    _, vjp = jax.vjp(
        lambda *a: _mega_decode_layer_ref(*a, head_dim, eps, scale), *res)
    return vjp(ct)


mega_decode_layer.defvjp(_mega_decode_layer_fwd, _mega_decode_layer_bwd)


def paged_copy_blocks(cache, src_blocks, dst_blocks):
    """Copy whole pages ``src_blocks[i] → dst_blocks[i]`` inside the
    paged pools — the device half of copy-on-write block sharing
    (serving/block_allocator.py).  Fixed-shape: pad unused entries with
    the out-of-range sentinel (``num_blocks``) — OOB destinations DROP
    and OOB sources clamp to a real page that is then never written.
    Shared cache-arity dispatch; returns the new cache tuple."""
    return tuple(a.at[dst_blocks].set(a[src_blocks]) for a in cache)


def variable_length_memory_efficient_attention(q, k, v, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False):
    """Varlen attention (reference cutlass memory_efficient_attention):
    here, flash/XLA attention with a length mask."""
    if mask is None and (seq_lens is not None or kv_seq_lens is not None):
        sk = k.shape[1]
        # mask only the KEY axis: fully-masked query rows would softmax over
        # all -inf and emit NaN; padded query outputs are instead left as
        # attention over the valid keys and callers drop them
        klens = kv_seq_lens if kv_seq_lens is not None else seq_lens
        km = jnp.arange(sk)[None] < klens[:, None]
        mask = jnp.where(km[:, None, None, :], 0.0, -jnp.inf)
    return F.scaled_dot_product_attention(q, k, v, attn_mask=mask,
                                          is_causal=causal)


def fused_moe(x, gate_weight, ffn1_weights, ffn2_weights, ffn1_biases=None,
              ffn2_biases=None, moe_topk=2, norm_topk_prob=True,
              act="silu_glu"):
    """Reference: paddle.incubate.nn.functional.fused_moe — one fused op
    for topk gating + per-expert FFN + weighted combine.

    TPU formulation: every token runs EVERY expert densely
    (``einsum('nh,ehi->nei')`` — weights stay (E, H, *), activations are
    the N×E×I transient) and the top-k probabilities zero out the
    non-selected experts in the combine.  Gathering per-token weight
    copies (``w[topi]``) would materialize N×K full weight matrices —
    terabytes at Mixtral scale.  The dense form trades E/K× extra FLOPs
    for static shapes and no routing; for large-scale training use
    MoELayer's capacity-based dispatch (distributed/moe.py), which is
    the ep-sharded production path.

    Shapes: x (..., H); gate_weight (H, E); ffn1_weights (E, H, 2I) for
    the silu-glu act (gate|up packed) or (E, H, I); ffn2_weights
    (E, I, H).  Returns (..., H).
    """
    import jax

    orig = x.shape
    H = orig[-1]
    t = x.reshape(-1, H)                                    # (N, H)
    logits = t.astype(jnp.float32) @ jnp.asarray(gate_weight,
                                                 jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                 # (N, E)
    E = probs.shape[-1]
    topv, topi = jax.lax.top_k(probs, moe_topk)             # (N, K)
    if norm_topk_prob:
        topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    # (N, E) combine weights: top-k probs scattered back, zeros elsewhere
    combine = jnp.sum(jax.nn.one_hot(topi, E, dtype=topv.dtype)
                      * topv[..., None], axis=1)            # (N, E)

    w1 = jnp.asarray(ffn1_weights)
    w2 = jnp.asarray(ffn2_weights)
    h1 = jnp.einsum("nh,ehi->nei", t, w1.astype(t.dtype))
    if ffn1_biases is not None:
        h1 = h1 + jnp.asarray(ffn1_biases)[None].astype(h1.dtype)
    if act == "silu_glu":
        gate_part, up = jnp.split(h1, 2, axis=-1)
        h1 = jax.nn.silu(gate_part) * up
    elif act == "gelu":
        h1 = jax.nn.gelu(h1)
    else:
        h1 = jax.nn.silu(h1)
    h2 = jnp.einsum("nei,eih->neh", h1, w2.astype(h1.dtype))
    if ffn2_biases is not None:
        h2 = h2 + jnp.asarray(ffn2_biases)[None].astype(h2.dtype)
    out = jnp.einsum("neh,ne->nh", h2, combine.astype(h2.dtype))
    return out.reshape(orig).astype(x.dtype)
