"""``paddle.incubate`` parity surface (SURVEY §2.6 incubate row).

- ``incubate.nn`` — FusedMultiTransformer + fused functional ops
- ``incubate.nn.functional`` — fused_rms_norm/fused_layer_norm/
  fused_bias_act/fused_rotary_position_embedding/masked_multihead_attention/
  paged_attention/variable_length_memory_efficient_attention
- expert-parallel MoE lives at ``paddle_tpu.distributed.moe`` (re-exported
  here for reference-path compatibility)
"""

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from ..distributed import moe as distributed_moe  # noqa: F401
from ..distributed.moe import MoELayer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401 — the
#   reference exports both at paddle.incubate top level too

# segment ops live in geometric; the reference exports them here too
from ..geometric import (  # noqa: F401
    segment_max, segment_mean, segment_min, segment_sum)
from ..geometric.sampling import (  # noqa: F401
    khop_sampler as graph_khop_sampler,
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors)
from .ops import (  # noqa: F401
    identity_loss, softmax_mask_fuse, softmax_mask_fuse_upper_triangle)
from . import asp  # noqa: F401
# reference: paddle.incubate.autograd re-exports the functional AD surface
from ..autograd import (  # noqa: F401
    hessian, jacobian, jvp, vjp)
from .. import autograd  # noqa: F401
