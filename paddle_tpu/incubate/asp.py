"""paddle_tpu.incubate.asp — automatic structured (n:m) sparsity.

Reference: python/paddle/incubate/asp/* (ASPHelper, create_mask,
check_sparsity, prune_model).  TPU note: n:m sparse tensor cores are a
GPU feature; on TPU the value of ASP is the *pruning workflow* (train →
mask → fine-tune), so masks are computed exactly (greedy best n-of-m by
magnitude, the reference's mask_1d algorithm) and applied as dense
masked weights.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

_EXCLUDED = set()


def calculate_density(x) -> float:
    """Fraction of non-zero entries (reference: asp.calculate_density)."""
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / max(1, x.size)


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """n:m mask along the last axis: keep the n largest-|w| of every m
    consecutive weights (reference mask_1d; mask_2d_greedy reduces to the
    same per-row rule on the reshaped view used here)."""
    t = np.asarray(tensor)
    flat = t.reshape(-1, m) if t.size % m == 0 else None
    if flat is None:
        raise ValueError(f"create_mask: tensor size {t.size} not divisible "
                         f"by m={m}")
    order = np.argsort(-np.abs(flat), axis=1)
    mask = np.zeros_like(flat, dtype=np.float32)
    np.put_along_axis(mask, order[:, :n], 1.0, axis=1)
    return jnp.asarray(mask.reshape(t.shape))


def check_sparsity(tensor, func_name="check_mask_1d", n=2, m=4) -> bool:
    """True iff every m-group has at most n non-zeros."""
    t = np.asarray(tensor)
    if t.size % m:
        return False
    groups = (np.abs(t.reshape(-1, m)) > 0).sum(axis=1)
    return bool((groups <= n).all())


def set_excluded_layers(param_names, main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every >=2-D weight of ``model`` (in place on the
    layer's parameters) and return {param_name: mask}.  Biases, norms and
    excluded layers are skipped, mirroring ASPHelper._is_supported_layer."""
    masks = {}
    for name, p in model.named_parameters():
        if name in _EXCLUDED or p.ndim < 2 or p.shape[-1] % m:
            continue
        mask = create_mask(p, func_name=mask_algo, n=n, m=m)
        masks[name] = mask
        holder, attr = model, name.split(".")
        for part in attr[:-1]:
            holder = getattr(holder, part)
        setattr(holder, attr[-1], jnp.asarray(p) * mask)
    return masks


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
