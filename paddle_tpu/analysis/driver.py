"""pdtpu-lint driver: scan a tree, run every rule, apply suppressions
and the committed baseline.

Two passes:

1. **pre-pass** over all parsed files building the
   :class:`TreeContext` — the fault-site registry (parsed from
   ``resilience/faults.py``), the ``# guarded_by:`` field annotations
   (tree-wide, so cross-module accesses are checked), and the
   docs/RESILIENCE.md sites tables;
2. **rule pass** per file, then the tree-level docs↔registry
   consistency check.

Pure stdlib; jax is never imported (the ``lint`` CI gate asserts it).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, ParsedFile
from .rules import ALL_RULES
from .rules import fault_sites as _fault_sites
from .rules import locks as _locks

__all__ = ["TreeContext", "LintResult", "analyze", "load_baseline",
           "DEFAULT_SCAN", "FAULTS_PY", "RESILIENCE_DOC"]

#: repo-relative roots scanned by default (tests are exempt — they
#: deliberately poke the internals every rule exists to protect)
DEFAULT_SCAN = ("paddle_tpu", "tools", "examples", "bench.py")
FAULTS_PY = os.path.join("paddle_tpu", "resilience", "faults.py")
RESILIENCE_DOC = os.path.join("docs", "RESILIENCE.md")


@dataclasses.dataclass
class TreeContext:
    """Cross-file facts shared with every rule's ``check(pf, ctx)``."""

    root: str
    fault_sites: Tuple[str, ...] = ()
    fault_excs: Tuple[str, ...] = ()
    guarded_fields: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # new, actionable (exit-1) findings
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_suppressions: List[str]    # warnings, never failures
    stale_baseline: List[str]
    errors: List[str]                # unparsable files
    files_scanned: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors


def _iter_py_files(root: str, paths: Sequence[str]):
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full) and full.endswith(".py"):
            yield full
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)


def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    return list(data.get("findings", data) if isinstance(data, dict)
                else data)


def _baseline_match(entry: dict, finding: Finding) -> bool:
    return entry.get("rule") == finding.rule \
        and entry.get("file") == finding.path \
        and entry.get("code", "") == finding.snippet


def analyze(root: str, paths: Optional[Sequence[str]] = None,
            baseline: Optional[List[dict]] = None,
            rules: Optional[Sequence[str]] = None) -> LintResult:
    """Run the analyzer over ``paths`` (repo-relative) under ``root``."""
    paths = list(paths) if paths else list(DEFAULT_SCAN)
    baseline = list(baseline or [])
    active = {r: m for r, m in ALL_RULES.items()
              if rules is None or r in rules}

    parsed: List[ParsedFile] = []
    errors: List[str] = []
    for full in _iter_py_files(root, paths):
        rel = os.path.relpath(full, root).replace(os.sep, "/")
        try:
            with open(full, encoding="utf-8") as f:
                src = f.read()
            parsed.append(ParsedFile(full, rel, src))
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{rel}: unparsable: {e}")

    ctx = TreeContext(root=root)
    faults_file = os.path.join(root, FAULTS_PY)
    if os.path.exists(faults_file):
        with open(faults_file, encoding="utf-8") as f:
            ctx.fault_sites, ctx.fault_excs = \
                _fault_sites.extract_registry(f.read())
    for pf in parsed:
        ctx.guarded_fields.update(_locks.extract_guarded_fields(pf))

    all_findings: List[Finding] = []
    for pf in parsed:
        for rule_id, mod in active.items():
            all_findings.extend(mod.check(pf, ctx))

    # tree-level: docs/RESILIENCE.md sites tables ↔ resilience.SITES
    if "fault-site" in active and ctx.fault_sites:
        all_findings.extend(_docs_consistency(root, ctx))

    findings, suppressed, baselined = [], [], []
    used_baseline = [False] * len(baseline)
    for f in all_findings:
        if f.suppressed:
            suppressed.append(f)
            continue
        hit = next((i for i, e in enumerate(baseline)
                    if not used_baseline[i] and _baseline_match(e, f)),
                   None)
        if hit is not None:
            used_baseline[hit] = True
            f.baselined = True
            baselined.append(f)
        else:
            findings.append(f)

    # a suppression is only provably stale when every rule it names
    # actually ran this pass — under a --rules subset the others were
    # never evaluated, and "remove the comment" advice would break the
    # next full gate run
    checked = set(active)
    all_ran = set(active) == set(ALL_RULES)
    stale_sup = []
    for pf in parsed:
        for sup in pf.suppressions:
            evaluated = all_ran if "all" in sup.rules \
                else sup.rules <= checked
            if not sup.used and evaluated:
                stale_sup.append(
                    f"{pf.rel_path}:{sup.line}: stale suppression "
                    f"(disable={','.join(sorted(sup.rules))}) — no "
                    "finding matches it any more; remove the comment")
    stale_base = [
        f"baseline entry matches no finding any more — drop it: "
        f"{e.get('rule')} @ {e.get('file')}: {e.get('code', '')!r}"
        for i, e in enumerate(baseline) if not used_baseline[i]]

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings=findings, suppressed=suppressed,
                      baselined=baselined, stale_suppressions=stale_sup,
                      stale_baseline=stale_base, errors=errors,
                      files_scanned=len(parsed))


def _docs_consistency(root: str, ctx: TreeContext) -> List[Finding]:
    doc_rel = RESILIENCE_DOC.replace(os.sep, "/")
    doc_path = os.path.join(root, RESILIENCE_DOC)
    out: List[Finding] = []
    if not os.path.exists(doc_path):
        return out
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    doc_sites = _fault_sites.extract_doc_sites(text)
    doc_names = {s for s, _ in doc_sites}
    for site, line in doc_sites:
        if site not in ctx.fault_sites:
            out.append(Finding(
                rule="fault-site", path=doc_rel, line=line, col=0,
                message=f"docs table lists {site!r} which is not in "
                        "resilience.SITES — stale doc or missing "
                        "registration",
                snippet=text.splitlines()[line - 1].strip()))
    for site in ctx.fault_sites:
        if site not in doc_names:
            out.append(Finding(
                rule="fault-site", path=doc_rel, line=1, col=0,
                message=f"registered site {site!r} is missing from the "
                        f"sites tables in {doc_rel} — document where it "
                        "fires and what recovery looks like",
                snippet="(sites tables)"))
    return out
