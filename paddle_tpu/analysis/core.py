"""Shared machinery for the pdtpu-lint rule engine.

Everything here is pure stdlib (``ast``, ``re``, ``dataclasses``) — the
analyzer must run on a box with no jax installed in well under the CI
gate's 30 s budget, so no rule may import ``paddle_tpu`` proper.  Facts
about the runtime (the fault-site registry, the hook-container names)
are recovered from the *scanned sources' ASTs*, never from imports.

The pieces:

- :class:`Finding` — one rule violation, with enough identity
  (rule, file, source snippet) for baseline matching to survive line
  drift.
- :class:`ParsedFile` — a parsed module: AST with parent links,
  raw lines, and the per-line ``# pdtpu-lint: disable=`` suppressions.
- expression keys (:func:`expr_key`) — a stable dotted string for
  ``Name``/``Attribute``/``[0]``-subscript chains (``self.kv.caches``,
  ``_obs_state.EMIT[0]``) so rules can compare "the same place" across
  statements without object identity.
- guard analysis (:func:`is_guarded`) — whether a use site is dominated
  by the one-falsy-check idiom (``if x is not None:`` /  ``if x:`` /
  ``x.f() if x is not None else ...`` / an ``if x is None: return``
  early exit), the contract the ``telemetry-overhead`` CI gate enforces
  dynamically.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "ParsedFile", "Suppression", "expr_key", "call_name",
    "is_guarded", "enclosing_statement", "enclosing_function",
    "stmt_position", "node_position", "int_literals",
    "scope_walk",
]

_SUPPRESS_RE = re.compile(
    r"#\s*pdtpu-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location.

    ``snippet`` (the stripped source line) plus ``rule`` and ``path``
    form the baseline identity: recorded findings keep matching after
    unrelated edits move the line number."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""
    suppressed: bool = False
    baselined: bool = False

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_baseline_entry(self) -> Dict[str, object]:
        return {"rule": self.rule, "file": self.path, "line": self.line,
                "code": self.snippet}


@dataclasses.dataclass
class Suppression:
    """One inline ``# pdtpu-lint: disable=<rules>`` comment."""

    line: int
    rules: Set[str]
    used: bool = False


class ParsedFile:
    """One scanned module: source, AST (with ``.parent`` backlinks on
    every node), and inline suppressions."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[int, ast.AST] = {}
        # one walk, cached: every rule iterates the whole module and
        # re-walking per rule dominated the analyzer's runtime
        self.nodes: List[ast.AST] = list(ast.walk(self.tree))
        for node in self.nodes:
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
        self._rule_cache: Dict[str, object] = {}
        self.suppressions: List[Suppression] = []
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.suppressions.append(Suppression(i, rules))

    # -- navigation --------------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def parents(self, node: ast.AST) -> Iterable[ast.AST]:
        p = self.parent(node)
        while p is not None:
            yield p
            p = self.parent(p)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    # -- findings ----------------------------------------------------------

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        f = Finding(rule=rule, path=self.rel_path, line=line, col=col,
                    message=message, snippet=self.line_text(line))
        sup = self._suppression_for(rule, node)
        if sup is not None:
            sup.used = True
            f.suppressed = True
        return f

    def _suppression_for(self, rule: str,
                         node: ast.AST) -> Optional[Suppression]:
        """A finding is suppressed by a ``disable=`` comment on any line
        of its enclosing statement, or on a standalone comment line
        directly above it.  The line-above form deliberately requires a
        comment-only line: a trailing comment on the PREVIOUS statement
        must not leak onto this one and silently mask its findings."""
        stmt = enclosing_statement(self, node) or node
        lo = getattr(stmt, "lineno", getattr(node, "lineno", 1))
        hi = getattr(stmt, "end_lineno", lo) or lo
        for sup in self.suppressions:
            if not (rule in sup.rules or "all" in sup.rules):
                continue
            if lo <= sup.line <= hi:
                return sup
            if sup.line == lo - 1 \
                    and self.line_text(sup.line).startswith("#"):
                return sup
        return None


# ---------------------------------------------------------------------------
# expression identity
# ---------------------------------------------------------------------------

def expr_key(node: ast.AST) -> Optional[str]:
    """Stable dotted key for a Name/Attribute/``[const]``-subscript
    chain: ``self.kv.caches``, ``_obs_state.EMIT[0]``.  ``None`` for
    anything whose identity a linear scan cannot track (call results,
    arbitrary subscripts)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = expr_key(node.value)
        return None if base is None else f"{base}.{node.attr}"
    if isinstance(node, ast.Subscript):
        base = expr_key(node.value)
        if base is None:
            return None
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, int):
            return f"{base}[{sl.value}]"
        return None
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Dotted name of a call's callee (``jax.jit``, ``obs.emit_event``)."""
    return expr_key(node.func)


def int_literals(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """An int, or tuple/list of ints, as a literal — the shapes
    ``donate_argnums``/``static_argnums`` take.  None if not literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


# ---------------------------------------------------------------------------
# positions / enclosing scopes
# ---------------------------------------------------------------------------

def node_position(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def stmt_position(node: ast.AST) -> Tuple[int, int]:
    """End position of a statement — loads *inside* the statement sort
    before it, loads on later lines after it."""
    return (getattr(node, "end_lineno", getattr(node, "lineno", 0)),
            getattr(node, "end_col_offset", 0))


def enclosing_statement(pf: ParsedFile,
                        node: ast.AST) -> Optional[ast.AST]:
    """The outermost simple statement containing ``node`` (the node
    whose parent holds a statement list)."""
    cur = node
    for p in pf.parents(node):
        if isinstance(p, (ast.Module, ast.FunctionDef,
                          ast.AsyncFunctionDef, ast.ClassDef, ast.If,
                          ast.For, ast.AsyncFor, ast.While, ast.With,
                          ast.AsyncWith, ast.Try, ast.ExceptHandler)):
            return cur
        cur = p
    return cur


def scope_walk(scope: ast.AST) -> Iterable[ast.AST]:
    """Walk ``scope``'s subtree EXCLUDING nested function/lambda bodies
    (they are scopes of their own — ``ast.walk`` cannot prune)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def enclosing_function(pf: ParsedFile, node: ast.AST):
    for p in pf.parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return p
    return None


# ---------------------------------------------------------------------------
# the one-falsy-check guard idiom
# ---------------------------------------------------------------------------

def _test_implies_live(test: ast.AST, key: str) -> bool:
    """Does ``test`` being truthy imply ``key`` is not None?"""
    if expr_key(test) == key:                       # if x:
        return True
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        left, op, right = test.left, test.ops[0], test.comparators[0]
        if isinstance(op, ast.IsNot) and expr_key(left) == key \
                and isinstance(right, ast.Constant) and right.value is None:
            return True                             # if x is not None:
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        return any(_test_implies_live(v, key) for v in test.values)
    return False


def _test_implies_dead(test: ast.AST, key: str) -> bool:
    """Does ``test`` being truthy imply ``key`` IS None (so the else
    branch / fallthrough has it live)?"""
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        left, op, right = test.left, test.ops[0], test.comparators[0]
        if isinstance(op, ast.Is) and expr_key(left) == key \
                and isinstance(right, ast.Constant) and right.value is None:
            return True                             # if x is None:
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and expr_key(test.operand) == key:
        return True                                 # if not x:
    return False


def _is_terminal(stmts: Sequence[ast.stmt]) -> bool:
    """Does this block always leave the enclosing suite (return/raise/
    continue/break as its last statement)?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _early_exit_guards(pf: ParsedFile, node: ast.AST, key: str) -> bool:
    """``if x is None: return`` (or raise/continue/break) earlier in any
    enclosing suite puts every later statement on the not-None path."""
    cur: ast.AST = node
    for p in pf.parents(node):
        for field in ("body", "orelse", "finalbody"):
            suite = getattr(p, field, None)
            if isinstance(suite, list) and cur in suite:
                idx = suite.index(cur)
                for prev in suite[:idx]:
                    if isinstance(prev, ast.If) \
                            and _test_implies_dead(prev.test, key) \
                            and _is_terminal(prev.body):
                        return True
        cur = p
    return False


def is_guarded(pf: ParsedFile, node: ast.AST, key: str) -> bool:
    """Is the use of ``key`` at ``node`` dominated by a falsy check —
    the ``observability/_state.py`` contract?

    Recognized forms (all of which appear in the live tree):

    - ``if x is not None: <use>`` / ``if x: <use>``
    - ``if x is not None and <more>: <use>``
    - ``if x is None: ... else: <use>`` / ``if not x: ... else: <use>``
    - ``<use> if x is not None else <fallback>`` (conditional expr)
    - ``if x is None: return`` earlier in the suite (early exit)
    - ``while <...> x is not None <...>: <use>``
    """
    child = node
    for p in pf.parents(node):
        if isinstance(p, ast.If) or isinstance(p, ast.While):
            in_body = _contains(p.body, child)
            in_orelse = _contains(getattr(p, "orelse", []), child)
            if in_body and _test_implies_live(p.test, key):
                return True
            if in_orelse and isinstance(p, ast.If) \
                    and _test_implies_dead(p.test, key):
                return True
        if isinstance(p, ast.IfExp):
            if (p.body is child or _in_subtree(p.body, node)) \
                    and _test_implies_live(p.test, key):
                return True
            if (p.orelse is child or _in_subtree(p.orelse, node)) \
                    and _test_implies_dead(p.test, key):
                return True
        if isinstance(p, ast.BoolOp) and isinstance(p.op, ast.And):
            # x is not None and x.f(): every operand after a live-check
            # only evaluates when the check passed
            for i, v in enumerate(p.values):
                if (v is child or _in_subtree(v, node)) and any(
                        _test_implies_live(u, key) for u in p.values[:i]):
                    return True
        child = p
    return _early_exit_guards(pf, node, key)


def _contains(suite: Sequence[ast.AST], node: ast.AST) -> bool:
    return any(s is node for s in suite)


def _in_subtree(root: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(root))
