"""donation-safety: no reads of a donated buffer after its dispatch.

The PR 1 crash class: ``jax.jit(..., donate_argnums=...)`` hands the
argument's buffer to XLA — after the call the Python object still
exists but its memory is gone (or reused as the output).  Reading it —
directly, or through an ``np.asarray``/zero-copy view taken earlier —
segfaults on CPU and silently corrupts on TPU.  The seed hit this twice
(donated TrainStep state read on resume; ``np.asarray`` views of
donated params), both fixed dynamically in PR 1; this rule catches the
pattern at review time.

What is checked, per function scope, in source order:

1. a call to a known-donating callable (``_jit.discover``: local /
   ``self.``-bound ``jax.jit(..., donate_argnums=...)`` results and
   their ``.lower().compile()`` executables) *poisons* the expression
   keys passed at the donated positional indices (``self.kv.caches``,
   ``state``) — plus any alias previously taken from them via plain
   assignment or ``np.asarray``/``jnp.asarray`` (the view class);
2. a later load of a poisoned key (or any deeper path under it) is a
   finding;
3. a store to the key (or a prefix of it) un-poisons — the normal
   ``self.kv.caches = self._step_fn(..., self.kv.caches, ...)`` /
   ``new, _ = f(state); state = new`` lifecycle never fires.

The scan is linear in line order, refined with suite ordering: a read
only counts as "after" a dispatch when their deepest common suite runs
the read's statement strictly later (so the two arms of an ``if``/
``else`` never poison each other), and the ``x = f(x)`` rebind idiom —
a store to the donated key in the dispatch statement itself — clears
the poison immediately.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ..core import (Finding, ParsedFile, call_name, expr_key,
                    enclosing_statement, node_position, stmt_position)
from . import _jit

RULE = "donation-safety"

_VIEW_CALLS = ("np.asarray", "jnp.asarray", "numpy.asarray", "asarray")


def _functions(pf: ParsedFile) -> Iterable[ast.AST]:
    for node in pf.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _store_keys(stmt: ast.AST) -> List[str]:
    """Expression keys (re)bound by a statement."""
    keys: List[str] = []
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.With):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    elif isinstance(stmt, ast.Delete):
        targets = list(stmt.targets)
    for tgt in targets:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                k = expr_key(elt)
                if k is not None:
                    keys.append(k)
        else:
            k = expr_key(tgt)
            if k is not None:
                keys.append(k)
    return keys


def _covers(stored: str, poisoned: str) -> bool:
    """Does a store to ``stored`` re-materialize ``poisoned``?"""
    return poisoned == stored or poisoned.startswith(stored + ".") \
        or poisoned.startswith(stored + "[")


def _under(key: str, poisoned: str) -> bool:
    """Is a load of ``key`` a read of (or through) ``poisoned``?"""
    return key == poisoned or key.startswith(poisoned + ".") \
        or key.startswith(poisoned + "[")


def check(pf: ParsedFile, ctx) -> Iterable[Finding]:
    jitted = _jit.discover(pf)
    donating = {k: j for k, j in jitted.items() if j.donate}
    if not donating:
        return
    for fn in _functions(pf):
        yield from _check_function(pf, fn, donating)


def _stmt_chain(pf: ParsedFile, node: ast.AST, fn: ast.AST) -> List[ast.stmt]:
    """Statement ancestors of ``node`` inside ``fn``, outermost first."""
    chain: List[ast.stmt] = []
    if isinstance(node, ast.stmt):
        chain.append(node)
    for p in pf.parents(node):
        if p is fn:
            break
        if isinstance(p, ast.stmt):
            chain.append(p)
    chain.reverse()
    return chain


def _suite_of(pf: ParsedFile, stmt: ast.stmt):
    """(field name, index) of ``stmt`` in its parent's suite."""
    p = pf.parent(stmt)
    for field in ("body", "orelse", "finalbody"):
        suite = getattr(p, field, None)
        if isinstance(suite, list):
            for i, s in enumerate(suite):
                if s is stmt:
                    return field, i
    return None, -1


def _ordered_after(pf: ParsedFile, fn: ast.AST, dispatch: ast.AST,
                   load: ast.AST) -> bool:
    """Does ``load`` execute after ``dispatch`` on a straight-line
    reading?  True only when their deepest common suite runs the load's
    statement strictly later — sibling branches of one ``if`` (and the
    dispatch statement itself) never count."""
    dc = _stmt_chain(pf, dispatch, fn)
    lc = _stmt_chain(pf, load, fn)
    if dc and isinstance(dc[-1], (ast.Return, ast.Raise)):
        return False    # control leaves the function at the dispatch
    for ds, ls in zip(dc, lc):
        if ds is ls:
            continue
        if pf.parent(ds) is not pf.parent(ls):
            return False        # e.g. try body vs except handler
        d_field, d_i = _suite_of(pf, ds)
        l_field, l_i = _suite_of(pf, ls)
        return d_field == l_field and l_i > d_i
    return False        # one contains the other (same statement)


def _check_function(pf: ParsedFile, fn: ast.AST,
                    donating) -> Iterable[Finding]:
    # gather events in source order
    loads: List[Tuple[Tuple[int, int], str, ast.AST]] = []
    stores: List[Tuple[Tuple[int, int], str]] = []
    aliases: List[Tuple[Tuple[int, int], str, str]] = []  # (pos, alias, src)
    dispatches = []   # (poison_pos, donated_keys, callee_key, call_node)

    own_stmts = set()
    for node in ast.walk(fn):
        if node is fn:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested scopes get their own pass / are opaque
            for sub in ast.walk(node):
                own_stmts.add(id(sub))
            continue
        if id(node) in own_stmts:
            continue
        if isinstance(node, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            # record only the outermost chain: for snap.sum both the
            # Name and the Attribute would otherwise double-report
            if not isinstance(pf.parent(node), ast.Attribute):
                k = expr_key(node)
                if k is not None:
                    loads.append((node_position(node), k, node))
        if isinstance(node, ast.stmt):
            stmt_end = stmt_position(node)
            for k in _store_keys(node):
                stores.append((stmt_end, k))
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt_key = expr_key(node.targets[0])
                src_key = None
                v = node.value
                if expr_key(v) is not None:
                    src_key = expr_key(v)
                elif isinstance(v, ast.Call) \
                        and call_name(v) in _VIEW_CALLS and v.args:
                    src_key = expr_key(v.args[0])
                if tgt_key and src_key:
                    aliases.append((stmt_end, tgt_key, src_key))
        if isinstance(node, ast.Call):
            callee = expr_key(node.func)
            j = donating.get(callee) if callee else None
            if j is not None:
                keys = []
                for idx in j.donate:
                    if idx < len(node.args):
                        k = expr_key(node.args[idx])
                        if k is not None:
                            keys.append((idx, k))
                if keys:
                    stmt = enclosing_statement(pf, node) or node
                    dispatches.append((stmt_position(stmt), keys,
                                       callee, node))

    for poison_pos, keys, callee, call in dispatches:
        for idx, key in keys:
            # aliases of the donated key taken BEFORE the dispatch are
            # views of the same buffer
            poisoned = {key}
            for apos, alias, src in aliases:
                if apos <= poison_pos and _under(src, key):
                    poisoned.add(alias)
            for pkey in poisoned:
                # the x = f(x) rebind idiom: a store in the dispatch
                # statement itself (spos == poison_pos) clears the key
                kill = min((spos for spos, skey in stores
                            if spos >= poison_pos and _covers(skey, pkey)),
                           default=(1 << 30, 0))
                if kill == poison_pos:
                    continue
                for lpos, lkey, lnode in loads:
                    if poison_pos < lpos < kill and _under(lkey, pkey) \
                            and _ordered_after(pf, fn, call, lnode):
                        via = "" if pkey == key else \
                            f" (a view of it taken at line " \
                            f"{_alias_line(aliases, pkey)})"
                        yield pf.finding(
                            RULE, lnode,
                            f"'{lkey}' is read after being donated to "
                            f"'{callee}' (donate_argnums position {idx}, "
                            f"dispatched at line {call.lineno}){via} — "
                            "the buffer is dead after dispatch; rebind "
                            "the result first (read-after-free, the PR 1 "
                            "crash class)")


def _alias_line(aliases, alias_key: str) -> int:
    for (line, _col), a, _s in aliases:
        if a == alias_key:
            return line
    return 0
