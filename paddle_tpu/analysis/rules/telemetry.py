"""unguarded-telemetry: hot-path emits sit behind ONE falsy check.

The zero-overhead contract (``observability/_state.py``,
``resilience/_state.py``, enforced dynamically by the
``telemetry-overhead`` CI gate at a handful of probed sites): with
telemetry/fault-injection disabled, a producer pays exactly one falsy
check — no registry lookups, no event dicts, no lock.  This rule checks
the *whole tree* statically: outside the ``observability`` and
``resilience`` packages, every use of

- a registry handle from ``obs.get_registry()``,
- a telemetry handle from ``obs.get_telemetry()``,
- a hook container read (``_obs_state.EMIT[0]``,
  ``_rs_state.FAULTS[0]``, ``MONITOR``/``COLLECTIVE``/``SPAN``/
  ``RECORDER``/``POSTMORTEM`` — bound to a local or used in place),

must be dominated by the falsy-check idiom recognized by
:func:`~..core.is_guarded` (``if x is not None:``, ``if x:``, the
conditional expression, the early-exit, or an ``and`` chain).

Sanctioned wrappers need no local guard — they ARE the one check:
``obs.emit_event(...)``, ``span(...)``, ``obs.enable/disable`` and the
``get_*`` accessors themselves.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional

from ..core import (Finding, ParsedFile, call_name, expr_key,
                    is_guarded, scope_walk)

RULE = "unguarded-telemetry"

_EXEMPT_PARTS = ("/observability/", "/resilience/")
_HOOKS = ("MONITOR", "COLLECTIVE", "EMIT", "SPAN", "RECORDER",
          "POSTMORTEM", "FAULTS", "TRACE")
_GETTERS = {
    "get_registry": "obs.get_registry()",
    "get_telemetry": "obs.get_telemetry()",
    "get_flight_recorder": "obs.get_flight_recorder()",
    "get_watchdog": "obs.get_watchdog()",
    "get_request_tracer": "obs.get_request_tracer()",
}


def _exempt(pf: ParsedFile) -> bool:
    p = "/" + pf.rel_path.replace("\\", "/")
    return any(part in p for part in _EXEMPT_PARTS)


def _hook_subscript_key(node: ast.AST) -> Optional[str]:
    """``<chain>.<HOOK>[0]`` → its expr key, else None."""
    if isinstance(node, ast.Subscript):
        key = expr_key(node)
        if key is None or not key.endswith("[0]"):
            return None
        base = key[:-3].rsplit(".", 1)[-1]
        if base in _HOOKS:
            return key
    return None


def _getter_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        cn = call_name(node)
        if cn is not None and cn.split(".")[-1] in _GETTERS:
            return cn.split(".")[-1]
    return None


def check(pf: ParsedFile, ctx) -> Iterable[Finding]:
    if _exempt(pf):
        return
    # per function scope: names bound from a getter / hook container
    for node in pf.nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Module)):
            continue
        yield from _check_scope(pf, node)


def _check_scope(pf: ParsedFile, scope: ast.AST) -> Iterable[Finding]:
    tracked: Dict[str, str] = {}     # local name -> origin description
    nodes = list(scope_walk(scope))
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            getter = _getter_name(node.value)
            hook = _hook_subscript_key(node.value)
            if getter is not None:
                tracked[name] = _GETTERS[getter]
            elif hook is not None:
                tracked[name] = hook
    for node in nodes:
        # 1. uses of tracked locals: attribute access or direct call
        use_key = None
        use_node = None
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in tracked:
            use_key, use_node = node.value.id, node
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id in tracked:
            use_key, use_node = node.func.id, node
        if use_node is not None:
            if not is_guarded(pf, use_node, use_key):
                yield pf.finding(
                    RULE, use_node,
                    f"'{use_key}' (from {tracked[use_key]}) is used "
                    "without a dominating falsy check — the disabled "
                    "path must cost exactly one 'if x is not None' "
                    "(observability/_state.py contract, telemetry-"
                    "overhead gate)")
            continue
        # 2. in-place hook-container use: _obs_state.EMIT[0](...) /
        #    chained getter use: obs.get_registry().counter(...)
        if isinstance(node, ast.Call):
            hook = _hook_subscript_key(node.func)
            if hook is not None and not is_guarded(pf, node, hook):
                yield pf.finding(
                    RULE, node,
                    f"direct call of hook container {hook} without a "
                    "dominating falsy check — it is None whenever "
                    "telemetry/fault-injection is disabled")
                continue
            if isinstance(node.func, ast.Attribute) \
                    and _getter_name(node.func.value) is not None:
                getter = _getter_name(node.func.value)
                yield pf.finding(
                    RULE, node,
                    f"chained use {getter}().{node.func.attr}(...) — "
                    "the getter returns None when telemetry is "
                    "disabled; bind it and guard with one falsy check")
