"""lock-discipline: ``# guarded_by:`` fields are only touched under
their lock.

The serving stack crosses threads in exactly one place — HTTP handler
threads submit/route while ONE engine-loop thread drives the step
(``serving/server.py``) — and the shared mutable state is serialized by
``ServingServer._lock``.  That contract lived only in a docstring; now
it is machine-checked, Clang-thread-safety style:

- a field annotated on its assignment line with
  ``# guarded_by: <lock>`` may only be loaded/stored

  * inside a ``with <...>.<lock>:`` block (any receiver — the analyzer
    matches the lock by its final attribute name),
  * inside a function annotated ``# requires-lock: <lock>`` (on the
    ``def`` line or the line above): the documented "caller must hold
    it / externally serialize" contract — e.g. every ``FrontDoor`` and
    ``Engine`` entry point, which the server only ever calls under its
    lock,
  * or inside ``__init__`` (construction precedes sharing).

Annotations are collected tree-wide in the driver pre-pass, so a module
reaching into another module's annotated field (``eng._states`` from
``frontdoor.py``) is checked too.  Fields are matched by attribute
name; keep annotated names unique across the tree (they are all
``_``-private today).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Optional

from ..core import Finding, ParsedFile, expr_key

RULE = "lock-discipline"

GUARDED_RE = re.compile(r"#\s*guarded_by:\s*([A-Za-z_][\w]*)")
REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_][\w]*)")


def extract_guarded_fields(pf: ParsedFile) -> Dict[str, str]:
    """``self.<field> = ...  # guarded_by: <lock>`` lines → field→lock."""
    fields: Dict[str, str] = {}
    ann_lines = {}
    for i, text in enumerate(pf.lines, start=1):
        m = GUARDED_RE.search(text)
        if m:
            ann_lines[i] = m.group(1)
    if not ann_lines:
        return fields
    for node in pf.nodes:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            end = getattr(node, "end_lineno", node.lineno) or node.lineno
            lock = next((ann_lines[ln] for ln in range(node.lineno, end + 1)
                         if ln in ann_lines), None)
            if lock is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Attribute):
                    fields[tgt.attr] = lock
    return fields


def _requires_lock(pf: ParsedFile, fn: ast.AST) -> Optional[str]:
    for line in (fn.lineno, fn.lineno - 1):
        m = REQUIRES_RE.search(pf.line_text(line))
        if m:
            return m.group(1)
    return None


def _with_lock_names(node: ast.With):
    for item in node.items:
        key = expr_key(item.context_expr)
        if key is not None:
            yield key.rsplit(".", 1)[-1]


def check(pf: ParsedFile, ctx) -> Iterable[Finding]:
    fields = ctx.guarded_fields
    if not fields:
        return
    for node in pf.nodes:
        if not isinstance(node, ast.Attribute) or node.attr not in fields:
            continue
        lock = fields[node.attr]
        ok = False
        for p in pf.parents(node):
            if isinstance(p, (ast.With, ast.AsyncWith)) \
                    and lock in _with_lock_names(p):
                ok = True
                break
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if p.name == "__init__" or _requires_lock(pf, p) == lock:
                    ok = True
                break
        if not ok:
            kind = "written" if isinstance(node.ctx, ast.Store) else "read"
            yield pf.finding(
                RULE, node,
                f"'{node.attr}' is guarded_by '{lock}' but {kind} "
                f"outside a 'with ...{lock}:' block (and the enclosing "
                f"function does not declare '# requires-lock: {lock}') "
                "— cross-thread access without the lock")
