"""pdtpu-lint rules.

Each rule module exposes ``RULE`` (its id) and ``check(pf, ctx)``
yielding :class:`~..core.Finding`s.  ``ctx`` is the
:class:`~..driver.TreeContext` — cross-file facts (the fault-site
registry parsed out of ``resilience/faults.py``, the ``guarded_by``
field annotations) collected in the driver's pre-pass.
"""

from __future__ import annotations

from . import (compat, donation, fault_sites, locks,  # noqa: F401
               retrace, telemetry)

#: rule id → module, in report order
ALL_RULES = {
    donation.RULE: donation,
    compat.RULE: compat,
    telemetry.RULE: telemetry,
    retrace.RULE: retrace,
    fault_sites.RULE: fault_sites,
    locks.RULE: locks,
}

__all__ = ["ALL_RULES"]
