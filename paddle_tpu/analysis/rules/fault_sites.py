"""fault-site: every named fault site exists — in code AND in the docs.

``resilience.SITES`` is the single registry of injectable fault sites;
a plan naming anything else is rejected at parse time, but a *producer*
calling the injector with a typo'd site (or a doc table drifting from
the registry) fails silently — the plan simply never fires and a chaos
run quietly loses coverage.  This rule pins all three surfaces to the
registry, which it recovers by PARSING ``resilience/faults.py`` (no
import — the analyzer must run jax-free):

- string literals fired at the injector (calls of a name bound from
  ``FAULTS[0]``, or of a callee named ``maybe_fault``/``_fault``) must
  be registered sites;
- fault-plan spec literals (``install_faults("step@3")``,
  ``parse_faults(...)``, ``FaultPlan("site", ...)``, and literal
  ``PDTPU_FAULTS`` env assignments) must parse under the grammar and
  name only registered sites and whitelisted exception types;
- ``site=`` keyword literals that LOOK like registry sites (a
  ``ckpt.``/``store.``/``serve.`` prefix, or exactly ``step``/
  ``collective``) must be registered — free-form retry labels
  (``site="supervisor"``) stay allowed;
- the sites tables in ``docs/RESILIENCE.md`` must list exactly the
  registered sites (both directions).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set

from ..core import Finding, ParsedFile, call_name, expr_key

RULE = "fault-site"

_ENTRY_RE = re.compile(r"^(?P<site>[\w.]+)@(?P<at>\d+)(?:x(?P<times>\d+))?$")
_SITE_LIKE = re.compile(
    r"^(ckpt|store|serve|cluster)\.[\w.]+$|^(step|collective)$")
_INJECTOR_CALLEES = ("maybe_fault", "_fault")
_PLAN_CALLEES = ("install_faults", "parse_faults")


def extract_registry(source: str):
    """``(SITES, exception names)`` parsed out of faults.py's AST."""
    tree = ast.parse(source)
    sites: List[str] = []
    excs: List[str] = []
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            if name == "SITES" and isinstance(stmt.value,
                                              (ast.Tuple, ast.List)):
                sites = [e.value for e in stmt.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)]
            elif name == "_EXC_NAMES" and isinstance(stmt.value, ast.Dict):
                excs = [k.value for k in stmt.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
    return tuple(sites), tuple(excs)


def extract_doc_sites(doc_text: str):
    """Site names from the ``| site | ... |`` tables in
    docs/RESILIENCE.md: ``[(site, line)]``."""
    out = []
    in_table = False
    for i, line in enumerate(doc_text.splitlines(), start=1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        first = cells[0].lower()
        if first == "site":
            in_table = True
            continue
        if in_table:
            if set(first) <= {"-", " ", ":"}:
                continue
            for tok in re.findall(r"`([\w.]+)`", cells[0]):
                out.append((tok, i))
    return out


def _spec_findings(pf: ParsedFile, node: ast.AST, spec: str,
                   sites, excs) -> Iterable[Finding]:
    for entry in re.split(r"[,;]", spec):
        entry = entry.strip()
        if not entry:
            continue
        head, _, exc_name = entry.partition(":")
        m = _ENTRY_RE.match(head.strip())
        if m is None:
            yield pf.finding(
                RULE, node,
                f"fault spec entry {entry!r} does not parse "
                "(grammar: site@index[xTimes][:ExcName])")
            continue
        if m.group("site") not in sites:
            yield pf.finding(
                RULE, node,
                f"fault spec names unregistered site "
                f"{m.group('site')!r} — registered: "
                f"{', '.join(sites)} (resilience/faults.py SITES)")
        if exc_name and exc_name.strip() not in excs:
            yield pf.finding(
                RULE, node,
                f"fault spec names unknown exception "
                f"{exc_name.strip()!r} — allowed: {', '.join(excs)}")


def _literal_strings(node: ast.AST) -> List[ast.Constant]:
    """String constants reachable through trivial expressions (a bare
    literal, or both arms of a conditional expression)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node]
    if isinstance(node, ast.IfExp):
        return _literal_strings(node.body) + _literal_strings(node.orelse)
    return []


def check(pf: ParsedFile, ctx) -> Iterable[Finding]:
    sites = ctx.fault_sites
    excs = ctx.fault_excs
    if not sites:
        return

    # which locals are FAULTS[0] bindings, per scope — collected
    # module-wide (the binding and the call share a function in every
    # real producer, and a name bound from FAULTS[0] anywhere is an
    # injector by construction)
    injector_names: Set[str] = set()
    for node in pf.nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            key = expr_key(node.value)
            if key is not None and key.endswith("FAULTS[0]"):
                injector_names.add(node.targets[0].id)

    for node in pf.nodes:
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        short = cn.split(".")[-1] if cn else ""
        callee_is_injector = (
            (isinstance(node.func, ast.Name)
             and node.func.id in injector_names)
            or short in _INJECTOR_CALLEES)
        if callee_is_injector and node.args:
            for lit in _literal_strings(node.args[0]):
                if lit.value not in sites:
                    yield pf.finding(
                        RULE, lit,
                        f"injector fired at unregistered site "
                        f"{lit.value!r} — the plan can never match; "
                        f"registered: {', '.join(sites)}")
        if short in _PLAN_CALLEES and node.args:
            for lit in _literal_strings(node.args[0]):
                yield from _spec_findings(pf, lit, lit.value, sites, excs)
        if short == "FaultPlan" and node.args:
            for lit in _literal_strings(node.args[0]):
                if lit.value not in sites:
                    yield pf.finding(
                        RULE, lit,
                        f"FaultPlan site {lit.value!r} is not "
                        f"registered; registered: {', '.join(sites)}")
        for kw in node.keywords:
            if kw.arg == "site":
                for lit in _literal_strings(kw.value):
                    if _SITE_LIKE.match(lit.value) \
                            and lit.value not in sites:
                        yield pf.finding(
                            RULE, lit,
                            f"site={lit.value!r} looks like a fault "
                            "site but is not in resilience.SITES — "
                            "typo, or register it")

    # literal PDTPU_FAULTS env assignments
    for node in pf.nodes:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.slice, ast.Constant) \
                        and tgt.slice.value == "PDTPU_FAULTS":
                    yield from _spec_findings(pf, node.value,
                                              node.value.value,
                                              sites, excs)
