"""retrace-hazard: nothing flows into a compiled callable that defeats
its cache.

The serving/TrainStep zero-recompile contract (the recompile sentinel's
bug class, PR 1/5): ONE compiled program per callable, re-dispatched
forever.  Four statically-checkable ways the tree has (nearly) broken
that:

- **R4a host-scalar feed**: ``float(x)`` / ``int(x)`` / ``x.item()`` /
  ``x.tolist()`` used directly as an argument to a known-jitted
  callable — a device→host sync on the hot path, and if the position is
  (or later becomes) static, a retrace per VALUE.
- **R4b jit-in-loop**: ``jax.jit(...)`` called inside a ``for``/
  ``while`` body — a fresh callable (fresh cache) per iteration; every
  dispatch recompiles.  Memoize the jitted callable outside the loop
  (the ``generation.py`` ``_decode_loop_memo`` pattern).
- **R4c mutable-global capture**: a jit-decorated function reading a
  module-level ``list``/``dict``/``set`` — the value is baked in at
  trace time, later mutations are silently ignored (or, via hashable
  wrappers, force a retrace).  Thread state through arguments instead.
- **R4d unhashable static**: a ``list``/``dict``/``set`` literal passed
  at a ``static_argnums`` position — unhashable, so every call dies (or
  the caller "fixes" it with a tuple whose contents still churn the
  cache).
- **R4f per-step draft-length scalar**: speculative decoding's draft
  length reaching a known-jitted callable as a fresh host ``int`` —
  ``len(draft)`` / a ``draft*``-named local bound to ``len(...)`` or
  ``int(...)`` — at a traced position.  The serving contract
  (docs/SERVING.md "Speculative decoding") is that per-slot draft
  length is DATA inside the fixed-shape span arrays (``span lens`` /
  ``tokens``) or a depth fixed at construction and warmup-compiled
  (static position); a per-step Python scalar is at best a host sync
  per dispatch and, the moment it shapes an array or turns static, a
  retrace per draft length — exactly the churn the draft-hit/miss mix
  produces every step.
- **R4e per-step tuned-config read**: ``ops.tuning.tuned_config(...)``
  called inside a loop body.  The tuned-config store is the SANCTIONED
  trace-time-frozen lookup (kernel wrappers and Engine construction
  resolve it once, before warmup — reading it inside a jit-traced
  function is fine and NOT flagged): its values bake into compiled
  programs by design.  A per-step read inside a dispatch loop breaks
  that contract both ways — it pretends the value can change mid-run
  (it cannot: the compiled program keeps what it traced), and if the
  value feeds a static position of a jitted callable, an actual change
  (``tuning.reload()``) retraces per new value.  Resolve the config
  before the loop.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..core import Finding, ParsedFile, call_name, expr_key, scope_walk
from . import _jit

RULE = "retrace-hazard"

_HOST_CALLS = ("float", "int")
_HOST_METHODS = ("item", "tolist")


def _host_scalar(node: ast.AST) -> str:
    """Describe ``node`` if it materializes a host scalar, else ''."""
    if isinstance(node, ast.Call):
        cn = call_name(node)
        if cn in _HOST_CALLS:
            return f"{cn}(...)"
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _HOST_METHODS:
            return f".{node.func.attr}()"
    return ""


def check(pf: ParsedFile, ctx) -> Iterable[Finding]:
    jitted = _jit.discover(pf)
    module_defs = {n.name for n in pf.tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
    mutable_globals: Set[str] = set()
    for stmt in pf.tree.body:
        if isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, (ast.List, ast.Dict, ast.Set,
                                            ast.ListComp, ast.DictComp,
                                            ast.SetComp)):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    mutable_globals.add(tgt.id)

    for node in pf.nodes:
        if isinstance(node, ast.Call):
            # R4a/R4d: calls of known-jitted callables
            callee = expr_key(node.func)
            j = jitted.get(callee) if callee else None
            if j is not None and j.static_unknown:
                j = None    # can't tell traced from static: stay silent
            if j is not None:
                for i, arg in enumerate(node.args):
                    desc = _host_scalar(arg)
                    if desc and i not in j.static:
                        yield pf.finding(
                            RULE, arg,
                            f"{desc} feeds traced position {i} of "
                            f"jitted '{callee}' — a device→host sync "
                            "per call on the dispatch path; pass the "
                            "device value (or make the position "
                            "static) instead")
                    # R4f is about a FRESH scalar per step: only calls
                    # inside a loop body can churn per step, so a
                    # one-shot construction-time feed stays silent
                    ddesc = "" if (i in j.static
                                   or not _inside_loop(pf, node)) \
                        else _draft_scalar(pf, arg)
                    if ddesc:
                        yield pf.finding(
                            RULE, arg,
                            f"draft length ({ddesc}) reaches traced "
                            f"position {i} of jitted '{callee}' as a "
                            "fresh Python int per step — per-slot "
                            "draft length must ride the step as DATA "
                            "inside the fixed-shape span arrays, or be "
                            "a depth fixed at construction and "
                            "warmup-compiled at a static position "
                            "(docs/SERVING.md \"Speculative "
                            "decoding\")")
                    if i in j.static and isinstance(
                            arg, (ast.List, ast.Dict, ast.Set)):
                        yield pf.finding(
                            RULE, arg,
                            f"unhashable {type(arg).__name__.lower()} "
                            f"literal at static position {i} of jitted "
                            f"'{callee}' — static args must be "
                            "hashable and stable or every call "
                            "retraces")
            # R4b: jax.jit inside a loop body
            if _jit.jit_call_of(node) is not None \
                    and _inside_loop(pf, node):
                yield pf.finding(
                    RULE, node,
                    "jax.jit(...) called inside a loop — a fresh "
                    "callable (and compile cache) per iteration; hoist "
                    "or memoize the jitted callable outside the loop")
            # R4e: tuned-config lookup inside a loop body (the
            # trace-time read — in a jitted function, a kernel wrapper,
            # or construction code — is the sanctioned idiom and stays
            # silent; see module docstring)
            if _is_tuned_config_call(node) and _inside_loop(pf, node):
                yield pf.finding(
                    RULE, node,
                    "tuned_config(...) read inside a loop — tuned "
                    "configs are trace-time-frozen (ops/tuning.py): "
                    "compiled programs keep the values they resolved "
                    "before warmup, so a per-step read is at best dead "
                    "and at worst a retrace per reload; resolve the "
                    "config once before the loop")

    # R4c: jitted module-level defs reading mutable module globals
    if mutable_globals:
        for key, j in jitted.items():
            fn_node = None
            if isinstance(j.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_node = j.node
            elif j.wrapped in module_defs:
                fn_node = next(n for n in pf.tree.body
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))
                               and n.name == j.wrapped)
            if fn_node is None:
                continue
            local_stores = {t.id for n in scope_walk(fn_node)
                            if isinstance(n, (ast.Assign,))
                            for t in n.targets if isinstance(t, ast.Name)}
            local_stores |= {a.arg for a in fn_node.args.args}
            for n in scope_walk(fn_node):
                if isinstance(n, ast.Name) \
                        and isinstance(n.ctx, ast.Load) \
                        and n.id in mutable_globals \
                        and n.id not in local_stores:
                    yield pf.finding(
                        RULE, n,
                        f"jit-compiled '{fn_node.name}' reads mutable "
                        f"module state '{n.id}' — the value is frozen "
                        "at trace time and later mutations are "
                        "silently ignored (the recompile-sentinel bug "
                        "class); pass it as an argument")


def _draftish(name: str) -> bool:
    """Identifier that names a speculative draft length/depth."""
    return "draft" in name.lower()


def _draft_scalar(pf: ParsedFile, arg: ast.AST) -> str:
    """Describe ``arg`` if it feeds a DRAFT length into a jitted call
    as a per-call Python scalar (R4f), else ''.

    Two shapes: a direct ``len(<draft-ish>)`` call, or a draft-ish
    NAME bound somewhere in the enclosing function from ``len(...)`` /
    ``int(...)``.  Array conversions (``jnp.asarray`` /
    ``np.asarray``), parameters, and constants are the sanctioned data
    path and stay silent — so does anything the pass cannot resolve
    (conservative: no guessing).  The caller additionally gates on the
    call sitting inside a loop body (a one-shot feed cannot churn
    per step)."""
    if isinstance(arg, ast.Call) and call_name(arg) == "len" \
            and arg.args:
        src = expr_key(arg.args[0]) or ""
        if _draftish(src):
            return f"len({src})"
        return ""
    if not (isinstance(arg, ast.Name) and _draftish(arg.id)):
        return ""
    fn = None
    for p in pf.parents(arg):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = p
            break
    if fn is None:
        return ""
    for n in scope_walk(fn):
        if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == arg.id
                for t in n.targets):
            v = n.value
            if isinstance(v, ast.Call) and call_name(v) in ("len", "int"):
                return f"'{arg.id}' = {call_name(v)}(...)"
    return ""


_CONFIG_ACCESSORS = ("tuned_config",)


def _is_tuned_config_call(node: ast.Call) -> bool:
    """``tuned_config(...)`` / ``tuning.tuned_config(...)`` — the
    sanctioned accessor's name, however it was imported."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in _CONFIG_ACCESSORS
    if isinstance(fn, ast.Attribute):
        return fn.attr in _CONFIG_ACCESSORS
    return False


def _inside_loop(pf: ParsedFile, node: ast.AST) -> bool:
    """Nearest loop ancestor is closer than the nearest enclosing
    function (a jit inside a def inside a loop is the def's business)."""
    for p in pf.parents(node):
        if isinstance(p, (ast.For, ast.AsyncFor, ast.While)):
            return True
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return False
    return False
