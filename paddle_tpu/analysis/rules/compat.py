"""compat-symbol: version-moved jax symbols route through core/compat.py.

The container pins jax 0.4.37 while the codebase targets the current
surface; the renamed/moved symbols (``shard_map`` — top-level with
``check_vma``/``axis_names`` vs ``jax.experimental.shard_map`` with
``check_rep``/``auto``; ``pltpu.CompilerParams`` vs
``TPUCompilerParams``) are shimmed in exactly one place,
``paddle_tpu/core/compat.py``.  A direct use anywhere else works on one
jax and breaks on the other — the class of breakage that took the seed
down (CHANGES.md, PR 1).

Flagged outside ``core/compat.py``:

- ``from jax.experimental.shard_map import ...`` /
  ``import jax.experimental.shard_map`` / ``from jax import shard_map``
- attribute uses ``jax.shard_map`` / ``jax.experimental.shard_map``
- ``pltpu.CompilerParams`` / ``pltpu.TPUCompilerParams`` (attribute or
  ``getattr(pltpu, "...")``) on any pallas-tpu module alias
- ``check_rep=`` / ``auto=`` keywords on a ``shard_map`` call — the
  0.4.37-only spelling; the compat wrapper takes ``check_vma=`` /
  ``axis_names=`` on every jax
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Finding, ParsedFile, call_name, expr_key

RULE = "compat-symbol"

_EXEMPT_SUFFIX = "core/compat.py"
_PARAMS = ("CompilerParams", "TPUCompilerParams")
_FIX = "route it through paddle_tpu/core/compat.py"


def _is_pallas_tpu(node: ast.AST) -> bool:
    key = expr_key(node)
    if key is None:
        return False
    return key == "pltpu" or "pallas" in key.split(".")


def check(pf: ParsedFile, ctx) -> Iterable[Finding]:
    if pf.rel_path.replace("\\", "/").endswith(_EXEMPT_SUFFIX):
        return
    for node in pf.nodes:
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax.experimental.shard_map":
                yield pf.finding(
                    RULE, node,
                    "import from jax.experimental.shard_map — moved to "
                    f"top-level jax in newer jax; {_FIX} "
                    "(compat.shard_map)")
            elif mod == "jax" and any(a.name == "shard_map"
                                      for a in node.names):
                yield pf.finding(
                    RULE, node,
                    "from jax import shard_map — absent on jax 0.4.37; "
                    f"{_FIX} (compat.shard_map)")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.experimental.shard_map":
                    yield pf.finding(
                        RULE, node,
                        "import jax.experimental.shard_map — "
                        f"version-moved; {_FIX} (compat.shard_map)")
        elif isinstance(node, ast.Attribute):
            key = expr_key(node)
            if key in ("jax.shard_map", "jax.experimental.shard_map"):
                yield pf.finding(
                    RULE, node,
                    f"direct use of {key} — version-moved symbol; "
                    f"{_FIX} (compat.shard_map)")
            elif node.attr in _PARAMS and _is_pallas_tpu(node.value):
                yield pf.finding(
                    RULE, node,
                    f"direct use of pltpu.{node.attr} — renamed across "
                    f"jax versions; {_FIX} "
                    "(compat.pallas_compiler_params())")
        elif isinstance(node, ast.Call):
            cn = call_name(node)
            if cn == "getattr" and len(node.args) >= 2 \
                    and _is_pallas_tpu(node.args[0]) \
                    and isinstance(node.args[1], ast.Constant) \
                    and node.args[1].value in _PARAMS:
                yield pf.finding(
                    RULE, node,
                    f"getattr(pltpu, {node.args[1].value!r}) — renamed "
                    f"across jax versions; {_FIX} "
                    "(compat.pallas_compiler_params())")
            elif cn is not None and cn.split(".")[-1] == "shard_map":
                for kw in node.keywords:
                    if kw.arg in ("check_rep", "auto"):
                        yield pf.finding(
                            RULE, node,
                            f"shard_map(..., {kw.arg}=) is the "
                            "jax-0.4.37-only spelling; call "
                            "compat.shard_map with check_vma=/"
                            "axis_names= instead")
