"""Shared discovery of jit-compiled callables in one module.

Both ``donation-safety`` and ``retrace-hazard`` need to know, per
module, *which names are compiled callables* and with what
``donate_argnums``/``static_argnums``.  The forms recognized (all live
in this tree):

- ``f = jax.jit(fn, donate_argnums=(2,))``            (local/module name)
- ``self._step_fn = jax.jit(step_fn, donate_argnums=(1,))``
  (instance attribute — registered class-wide, so a call in another
  method of the same class resolves)
- ``@jax.jit`` / ``@functools.partial(jax.jit, static_argnames=...)``
  decorated defs (``static_argnames`` are resolved to positions against
  the wrapped def's signature; unresolvable names set
  ``static_unknown`` so rules stay silent rather than misclassify)
- ``g = f.lower(...).compile()`` — the AOT executable inherits ``f``'s
  donation vector
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from ..core import ParsedFile, call_name, expr_key, int_literals

__all__ = ["JittedCallable", "discover", "jit_call_of"]

_JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit")


@dataclasses.dataclass
class JittedCallable:
    """One known-compiled callable binding."""

    key: str                     # expr key it is bound to (may be self.X)
    donate: Tuple[int, ...]      # donated positional indices
    static: Tuple[int, ...]      # static positional indices
    node: ast.AST                # the jax.jit(...) call (or def) site
    wrapped: Optional[str] = None   # name of the wrapped function, if a Name
    # static_argnames present but the named positions could not be
    # resolved (no visible wrapped def): rules must not classify any
    # position of this callable as traced-vs-static
    static_unknown: bool = False


def jit_call_of(node: ast.AST) -> Optional[ast.Call]:
    """``node`` as a ``jax.jit(...)``/``jit(...)`` call, else None."""
    if isinstance(node, ast.Call) and call_name(node) in _JIT_NAMES:
        return node
    return None


def _argnums(call: ast.Call, kw_name: str) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == kw_name:
            lits = int_literals(kw.value)
            return lits if lits is not None else ()
    return ()


def _argnames(call: ast.Call, kw_name: str) -> Optional[Tuple[str, ...]]:
    """String-literal tuple/list (or single string) of ``kw_name``;
    None when the keyword is absent, () when present but non-literal."""
    for kw in call.keywords:
        if kw.arg != kw_name:
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in v.elts):
            return tuple(e.value for e in v.elts)
        return ()
    return None


def _resolve_static(call: ast.Call, fn_def) -> Tuple[Tuple[int, ...], bool]:
    """(static positional indices, unknown?) from static_argnums and/or
    static_argnames, resolving names against ``fn_def``'s parameters."""
    static = list(_argnums(call, "static_argnums"))
    names = _argnames(call, "static_argnames")
    unknown = False
    if names:
        if fn_def is not None:
            params = [a.arg for a in fn_def.args.args]
            for n in names:
                if n in params:
                    static.append(params.index(n))
                else:
                    unknown = True      # kw-only / unknown name
        else:
            unknown = True              # no visible signature to map
    return tuple(sorted(set(static))), unknown


def _normalize_key(target: ast.AST) -> Optional[str]:
    """Binding key for a jit assignment target.  Instance attributes
    are normalized to ``self.<attr>`` so discovery in ``__init__`` /
    ``_build_fns`` matches calls in other methods of the class."""
    key = expr_key(target)
    if key is None:
        return None
    parts = key.split(".")
    if parts[0] == "self" and len(parts) == 2:
        return key
    return key


def discover(pf: ParsedFile) -> Dict[str, JittedCallable]:
    """All jit-compiled callable bindings in the module, keyed by the
    expression they are bound to.  Memoized per file — both the
    donation and retrace rules need it."""
    cached = pf._rule_cache.get("jit")
    if cached is not None:
        return cached
    found: Dict[str, JittedCallable] = {}
    defs_by_name = {n.name: n for n in reversed(pf.nodes)
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}

    for node in pf.nodes:
        # f = jax.jit(...) / self.f = jax.jit(...)
        if isinstance(node, ast.Assign):
            call = jit_call_of(node.value)
            if call is not None:
                wrapped = None
                if call.args and isinstance(call.args[0], ast.Name):
                    wrapped = call.args[0].id
                static, unknown = _resolve_static(
                    call, defs_by_name.get(wrapped))
                for tgt in node.targets:
                    key = _normalize_key(tgt)
                    if key is not None:
                        found[key] = JittedCallable(
                            key, _argnums(call, "donate_argnums"),
                            static, call, wrapped,
                            static_unknown=unknown)
            continue
        # @jax.jit / @functools.partial(jax.jit, ...) decorated defs
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                donate: Tuple[int, ...] = ()
                static: Tuple[int, ...] = ()
                unknown = False
                hit = False
                if expr_key(dec) in _JIT_NAMES:
                    hit = True
                elif isinstance(dec, ast.Call) and (
                        call_name(dec) in _JIT_NAMES
                        or (call_name(dec) in ("functools.partial",
                                               "partial")
                            and dec.args
                            and expr_key(dec.args[0]) in _JIT_NAMES)):
                    hit = True
                    donate = _argnums(dec, "donate_argnums")
                    static, unknown = _resolve_static(dec, node)
                if hit:
                    found[node.name] = JittedCallable(
                        node.name, donate, static, node, node.name,
                        static_unknown=unknown)
                    break

    # g = f.lower(...).compile(): inherit f's donation vector
    for node in pf.nodes:
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "compile" \
                and isinstance(v.func.value, ast.Call) \
                and isinstance(v.func.value.func, ast.Attribute) \
                and v.func.value.func.attr == "lower":
            src = _normalize_key(v.func.value.func.value)
            if src in found:
                for tgt in node.targets:
                    key = _normalize_key(tgt)
                    if key is not None:
                        found[key] = JittedCallable(
                            key, found[src].donate, found[src].static,
                            v, found[src].wrapped,
                            static_unknown=found[src].static_unknown)
    pf._rule_cache["jit"] = found
    return found
