"""pdtpu-lint: framework-invariant static analysis (docs/ANALYSIS.md).

An AST-based analyzer (stdlib ``ast`` only — importable and runnable
with no jax on the box) that encodes the framework invariants this
repo's hardest bugs have violated, as machine-checked rules:

==================  =====================================================
rule                invariant
==================  =====================================================
donation-safety     no reads of a buffer after it was donated to a
                    compiled callable (the PR 1 read-after-free class)
compat-symbol       version-moved jax symbols only via core/compat.py
unguarded-telemetry observability/resilience hooks behind ONE falsy
                    check outside their packages (zero-overhead
                    contract)
retrace-hazard      nothing feeds a compiled callable that defeats its
                    cache (host scalars, jit-in-loop, mutable-global
                    capture, unhashable statics)
fault-site          fault sites exist in resilience.SITES and in the
                    docs/RESILIENCE.md tables — both directions
lock-discipline     ``# guarded_by:`` fields only touched under their
                    lock or in ``# requires-lock:`` functions
==================  =====================================================

Suppress a deliberate violation inline::

    ...  # pdtpu-lint: disable=<rule> — <why>

Pre-existing findings live in ``tools/lint_baseline.json`` (matched by
rule + file + source line text, so they survive line drift); the
``lint`` CI gate (``python tools/ci.py --only lint``) fails on any NEW
finding and warns on stale suppressions/baseline entries so the
baseline only shrinks.  CLI: ``python tools/pdtpu_lint.py``.

This package is deliberately NOT imported by ``paddle_tpu/__init__``:
it is a dev tool, not user API, and it must load without jax.
"""

from __future__ import annotations

from .core import Finding, ParsedFile  # noqa: F401
from .driver import (DEFAULT_SCAN, LintResult, TreeContext,  # noqa: F401
                     analyze, load_baseline)
from .rules import ALL_RULES  # noqa: F401

__all__ = ["Finding", "ParsedFile", "LintResult", "TreeContext",
           "analyze", "load_baseline", "ALL_RULES", "DEFAULT_SCAN"]
