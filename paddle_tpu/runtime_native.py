"""ctypes bindings for the C++ runtime library (paddle_tpu/native/
pdtpu_native.cpp).

Reference parity: the reference's TCPStore, reader blocking queue, and
tensor collation are C++ (SURVEY §2.4 store row, §2.6 data pipeline row);
this module is their TPU-host equivalent. Everything degrades gracefully:
``available()`` is False when the library isn't built and callers fall back
to pure Python (launch/store.py, io collate).

Build: ``make -C paddle_tpu/native`` (done automatically on first import
when a toolchain is present). The .so lands next to the sources when that
directory is writable (repo checkout / venv), else in
``~/.cache/paddle_tpu`` (read-only site-packages install).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")


def _build_dir() -> str:
    if os.access(_NATIVE_DIR, os.W_OK):
        return os.path.join(_NATIVE_DIR, "build")
    # shared per-user cache: key by source content, not mtime — wheel
    # timestamps are normalized (SOURCE_DATE_EPOCH), so after an upgrade a
    # stale .so would otherwise read as fresh and be dlopened against new
    # bindings
    import hashlib
    with open(os.path.join(_NATIVE_DIR, "pdtpu_native.cpp"), "rb") as f:
        key = hashlib.sha1(f.read()).hexdigest()[:12]
    return os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.expanduser("~/.cache")),
        "paddle_tpu", f"native-build-{key}")


_SO_PATH = os.path.join(_build_dir(), "libpdtpu_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _is_fresh() -> bool:
    src = os.path.join(_NATIVE_DIR, "pdtpu_native.cpp")
    return (os.path.exists(_SO_PATH)
            and os.path.getmtime(_SO_PATH) >= os.path.getmtime(src))


def _try_build() -> bool:
    global _build_attempted
    if _build_attempted:
        return os.path.exists(_SO_PATH)
    _build_attempted = True
    # Cross-process exclusive lock: N launched workers on one host must not
    # run `make` concurrently into the same .so, and none may dlopen a
    # half-written file — so even the freshness check happens under the
    # lock (a sibling could be mid-link when we see the path exist).
    import fcntl
    build = os.path.dirname(_SO_PATH)
    os.makedirs(build, exist_ok=True)
    lock_path = os.path.join(build, ".build_lock")
    try:
        with open(lock_path, "w") as lock_f:
            fcntl.lockf(lock_f, fcntl.LOCK_EX)
            try:
                if _is_fresh():
                    return True
                subprocess.run(["make", "-C", _NATIVE_DIR,
                                f"BUILD={build}"], check=True,
                               capture_output=True, timeout=120)
                return os.path.exists(_SO_PATH)
            finally:
                fcntl.lockf(lock_f, fcntl.LOCK_UN)
    except Exception:
        return False


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        try:
            if not _try_build():
                return None
            lib = ctypes.CDLL(_SO_PATH)
            _bind(lib)
        except Exception:
            return None  # degrade to the pure-Python fallbacks
        _lib = lib
        return _lib


def _bind(lib):
    # inside the caller's try: a mismatched .so (missing symbol →
    # AttributeError) must degrade like any other load failure
    lib.pdtpu_store_server_create.restype = ctypes.c_void_p
    lib.pdtpu_store_server_start.restype = ctypes.c_int
    lib.pdtpu_store_server_start.argtypes = [ctypes.c_void_p,
                                             ctypes.c_char_p,
                                             ctypes.c_int]
    lib.pdtpu_store_server_destroy.argtypes = [ctypes.c_void_p]
    lib.pdtpu_queue_create.restype = ctypes.c_void_p
    lib.pdtpu_queue_create.argtypes = [ctypes.c_size_t]
    lib.pdtpu_queue_push.restype = ctypes.c_int
    lib.pdtpu_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_size_t, ctypes.c_double]
    lib.pdtpu_queue_pop.restype = ctypes.POINTER(ctypes.c_char)
    lib.pdtpu_queue_pop.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_size_t),
                                    ctypes.c_double,
                                    ctypes.POINTER(ctypes.c_int)]
    lib.pdtpu_queue_close.argtypes = [ctypes.c_void_p]
    lib.pdtpu_queue_size.restype = ctypes.c_size_t
    lib.pdtpu_queue_size.argtypes = [ctypes.c_void_p]
    lib.pdtpu_queue_destroy.argtypes = [ctypes.c_void_p]
    lib.pdtpu_block_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.pdtpu_collate_stack.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_size_t, ctypes.c_size_t]


def available() -> bool:
    return _load() is not None


class StoreServer:
    """C++ TCPStore server (drop-in for launch.store._StoreServer)."""

    def __init__(self, host: str, port: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("pdtpu_native not built")
        self._lib = lib
        self._h = lib.pdtpu_store_server_create()
        self.port = lib.pdtpu_store_server_start(
            self._h, host.encode(), int(port))
        if self.port < 0:
            lib.pdtpu_store_server_destroy(self._h)
            self._h = None
            raise OSError(f"cannot bind store server on {host}:{port}")

    def close(self):
        if self._h is not None:
            self._lib.pdtpu_store_server_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class BlockingQueue:
    """Bounded MPMC byte-block queue (the reference reader-queue role)."""

    def __init__(self, capacity: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("pdtpu_native not built")
        self._lib = lib
        self._h = lib.pdtpu_queue_create(capacity)

    def push(self, data: bytes, timeout: float = 60.0) -> bool:
        r = self._lib.pdtpu_queue_push(self._h, data, len(data),
                                       float(timeout))
        if r == -2:
            raise RuntimeError("queue closed")
        if r == -3:
            raise MemoryError("native queue: block allocation failed")
        return r == 0

    def pop(self, timeout: float = 60.0) -> Optional[bytes]:
        size = ctypes.c_size_t()
        status = ctypes.c_int()
        p = self._lib.pdtpu_queue_pop(self._h, ctypes.byref(size),
                                      float(timeout), ctypes.byref(status))
        if not p:
            if status.value == -2:
                return None       # closed and drained
            raise TimeoutError("queue pop timed out")
        try:
            return ctypes.string_at(p, size.value)
        finally:
            self._lib.pdtpu_block_free(p)

    def close(self):
        if self._h is not None:
            self._lib.pdtpu_queue_close(self._h)

    def __len__(self):
        return int(self._lib.pdtpu_queue_size(self._h))

    def destroy(self):
        if self._h is not None:
            self._lib.pdtpu_queue_destroy(self._h)
            self._h = None


def collate_stack(arrays: List[np.ndarray]) -> Optional[np.ndarray]:
    """np.stack for a list of same-shape/dtype contiguous arrays via the
    C++ memcpy loop (GIL released during the copy). Returns None when the
    fast path doesn't apply (caller falls back to np.stack)."""
    lib = _load()
    if lib is None or not arrays:
        return None
    a0 = arrays[0]
    if a0.dtype.hasobject:
        # memcpy of PyObject* would copy borrowed references → corruption
        return None
    if not all(isinstance(a, np.ndarray) and a.shape == a0.shape
               and a.dtype == a0.dtype and a.flags.c_contiguous
               for a in arrays):
        return None
    n = len(arrays)
    out = np.empty((n, *a0.shape), a0.dtype)
    srcs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
    lib.pdtpu_collate_stack(out.ctypes.data_as(ctypes.c_void_p), srcs, n,
                            a0.nbytes)
    return out
