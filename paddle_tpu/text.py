"""paddle_tpu.text — NLP datasets + Viterbi decoding.

Reference: python/paddle/text/ (datasets/* and viterbi_decode.py).

Viterbi is the real compute here and is implemented as a ``lax.scan``
dynamic program (one pass over time, argmax backtrace on the reverse
pass) — compiles once, runs on-chip.  Datasets read from local files
(zero-egress environment): every class takes ``data_file`` pointing at
the upstream-format archive member and raises with the expected format
when absent.
"""

from __future__ import annotations

import os
import tarfile

import numpy as np
import jax
import jax.numpy as jnp

from .io import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]


# ---------------------------------------------------------------------------
# Viterbi decode (reference: paddle.text.viterbi_decode / ViterbiDecoder)
# ---------------------------------------------------------------------------

def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Max-score tag path under a linear-chain CRF.

    potentials (B, T, N) emission scores, transition_params (N, N) with
    ``trans[i, j]`` = score of moving FROM tag j TO tag i (the reference's
    convention), lengths (B,) valid steps.  Returns (scores, paths
    (B, T) int64 with zeros past each length).

    With ``include_bos_eos_tag`` the last two tags are BOS/EOS: BOS→first
    and last→EOS transitions are added, as in the reference.
    """
    em = jnp.asarray(potentials, jnp.float32)
    trans = jnp.asarray(transition_params, jnp.float32)
    B, T, N = em.shape
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)

    if include_bos_eos_tag:
        bos, eos = N - 2, N - 1
        alpha0 = em[:, 0] + trans[:, bos][None, :]
    else:
        alpha0 = em[:, 0]

    ts = jnp.arange(1, T)

    def step(alpha, inp):
        em_t, t = inp
        # scores[b, i, j] = alpha[b, j] + trans[i, j]
        scores = alpha[:, None, :] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=-1)          # (B, N)
        new_alpha = jnp.max(scores, axis=-1) + em_t
        # steps past a sequence's length keep its alpha frozen
        live = (t < lengths)[:, None]
        new_alpha = jnp.where(live, new_alpha, alpha)
        return new_alpha, (best_prev, live)

    alpha, (backptr, lives) = jax.lax.scan(
        step, alpha0, (jnp.swapaxes(em, 0, 1)[1:], ts))

    if include_bos_eos_tag:
        alpha = alpha + trans[eos, :][None, :]

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)

    def back(tag, inp):
        bp_t, live_t = inp
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        prev = jnp.where(live_t[:, 0], prev.astype(jnp.int32), tag)
        # emit the CURRENT tag at this position, then move to prev
        return prev, jnp.where(live_t[:, 0], tag, -1)

    first_tag, rev_tags = jax.lax.scan(back, last_tag, (backptr, lives),
                                       reverse=True)
    # rev_tags[t] is the tag at position t+1 (−1 past length); position 0
    # is first_tag
    paths = jnp.concatenate([first_tag[:, None], jnp.swapaxes(rev_tags, 0, 1)],
                            axis=1)
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    paths = jnp.where(mask, paths, 0).astype(jnp.int64)
    return scores, paths


class ViterbiDecoder:
    """Layer form (reference: paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = jnp.asarray(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ---------------------------------------------------------------------------
# datasets (local-file backed)
# ---------------------------------------------------------------------------

class _LocalFileDataset(Dataset):
    EXPECT = "a local copy of the upstream archive"

    def __init__(self, data_file=None, mode="train", **kw):
        if not data_file or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{type(self).__name__}: downloads are disabled in this "
                f"environment — pass data_file={self.EXPECT}")
        self.mode = mode
        self.data = self._load(data_file)

    def _load(self, data_file):
        raise NotImplementedError

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class UCIHousing(_LocalFileDataset):
    """13 features + target per row, whitespace-separated (upstream
    housing.data format); features min-max normalised like the
    reference."""

    EXPECT = "the UCI housing.data file"

    def _load(self, data_file):
        raw = np.loadtxt(data_file, dtype=np.float32)
        x, y = raw[:, :-1], raw[:, -1:]
        lo, hi = x.min(0), x.max(0)
        x = (x - lo) / np.maximum(hi - lo, 1e-8)
        split = int(0.8 * len(x))
        sl = slice(0, split) if self.mode == "train" else slice(split, None)
        return [(x[i], y[i]) for i in range(len(x))[sl]]


class Imdb(_LocalFileDataset):
    """aclImdb tar: pos/neg text reviews; yields (token_id_list, label)
    with a whitespace vocabulary built from the train split."""

    EXPECT = "the aclImdb_v1.tar.gz archive"

    def _load(self, data_file):
        out = []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                parts = m.name.split("/")
                if len(parts) >= 4 and parts[1] == self.mode and \
                        parts[2] in ("pos", "neg") and m.isfile():
                    text = tf.extractfile(m).read().decode("utf8",
                                                           "ignore")
                    out.append((text.lower().split(),
                                1 if parts[2] == "pos" else 0))
        return out


class Imikolov(_LocalFileDataset):
    """PTB n-gram dataset (simple-examples tar); yields n-gram tuples."""

    EXPECT = "the simple-examples.tgz PTB archive"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", **kw):
        self.window_size = window_size
        super().__init__(data_file, mode=mode)

    def _load(self, data_file):
        name = ("simple-examples/data/ptb.train.txt" if self.mode == "train"
                else "simple-examples/data/ptb.valid.txt")
        with tarfile.open(data_file) as tf:
            text = tf.extractfile(name).read().decode("utf8")
        words = text.replace("\n", " <eos> ").split()
        vocab = {w: i for i, w in enumerate(sorted(set(words)))}
        ids = [vocab[w] for w in words]
        n = self.window_size
        return [tuple(ids[i:i + n]) for i in range(len(ids) - n + 1)]


class Movielens(_LocalFileDataset):
    """ml-1m ratings: yields (user_id, movie_id, rating)."""

    EXPECT = "the ml-1m.zip archive (or extracted ratings.dat)"

    def _load(self, data_file):
        import io as _io
        import zipfile
        if zipfile.is_zipfile(data_file):
            with zipfile.ZipFile(data_file) as zf:
                raw = zf.read("ml-1m/ratings.dat").decode("utf8")
        else:
            raw = open(data_file, encoding="utf8").read()
        rows = []
        for line in raw.strip().splitlines():
            u, m, r, _ = line.split("::")
            rows.append((int(u), int(m), float(r)))
        return rows


class Conll05st(_LocalFileDataset):
    """CoNLL-2005 SRL: yields (words, predicate, labels) triples from the
    upstream props/words column files packed in a tar."""

    EXPECT = "the conll05st tar archive"

    def _load(self, data_file):
        out = []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if m.isfile() and m.name.endswith(".txt"):
                    body = tf.extractfile(m).read().decode("utf8", "ignore")
                    sent = [l.split() for l in body.splitlines() if l.strip()]
                    if sent:
                        out.append(sent)
        return out


class WMT14(_LocalFileDataset):
    """WMT'14 en-fr: yields (src_ids, trg_ids, trg_next_ids) from the
    upstream tar of tokenised parallel text."""

    EXPECT = "the wmt14 tar archive of tokenised parallel text"

    def _load(self, data_file):
        pairs = []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if m.isfile() and self.mode in m.name:
                    body = tf.extractfile(m).read().decode("utf8", "ignore")
                    for line in body.splitlines():
                        if "\t" in line:
                            src, trg = line.split("\t")[:2]
                            pairs.append((src.split(), trg.split()))
        return pairs


class WMT16(WMT14):
    EXPECT = "the wmt16 tar archive of tokenised parallel text"
