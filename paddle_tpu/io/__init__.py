"""Data pipeline: Dataset / Sampler / DataLoader.

Paddle-parity surface of ``paddle.io`` (reference: python/paddle/io/ —
``dataloader/dataloader_iter.py``, ``worker.py``, ``batch_sampler.py``).

TPU-first execution model, deliberately different from the reference's
multiprocess shared-memory queue design:

- The hot loop on TPU is *compiled steps consuming device arrays*; what the
  loader must guarantee is that the next batch is already collated (host) and
  ideally already transferred (device) when step N finishes.  A bounded
  thread-pool prefetcher feeding a queue achieves that without the
  fork/shared-memory machinery the reference needs to dodge the GIL for
  Python-heavy CV decoding (numpy collate releases the GIL).
- Multi-host input sharding is first-class: ``DistributedBatchSampler``
  defaults its replica/rank to the jax process topology, so each host reads
  only its shard (reference: ``DistributedBatchSampler`` over PADDLE_TRAINER_*
  env).
- Native fast path: batch collation uses the C++ GIL-released memcpy
  (paddle_tpu.runtime_native.collate_stack) when built, so the prefetch
  thread pool scales; the cross-thread handoff itself stays a Python queue
  (its waits already release the GIL — a byte queue would only add
  serialization). runtime_native.BlockingQueue (the reference's
  paddle/fluid/operators/reader/ blocking-queue role) is exported as a
  public building block for user-built streaming pipelines.
"""

from __future__ import annotations

import bisect
import itertools
import math
import queue
import threading
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import jax
import numpy as np

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ConcatDataset", "ChainDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "SubsetRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "default_collate_fn", "get_worker_info",
]


# ---------------------------------------------------------------------------
# datasets (reference: python/paddle/io/dataset.py)
# ---------------------------------------------------------------------------

class Dataset:
    """Map-style dataset: implement ``__getitem__`` and ``__len__``."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    """Stream-style dataset: implement ``__iter__``."""

    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence[Any]):
        lens = {int(np.shape(t)[0]) for t in tensors}
        if len(lens) != 1:
            raise ValueError("all tensors must share dim-0 size, got %s" % lens)
        self.tensors = list(tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return int(np.shape(self.tensors[0])[0])


class ComposeDataset(Dataset):
    """Zip several same-length map datasets into one (fields concatenated)."""

    def __init__(self, datasets: Sequence[Dataset]):
        if not datasets:
            raise ValueError("datasets must be non-empty")
        if len({len(d) for d in datasets}) != 1:
            raise ValueError("datasets must share length")
        self.datasets = list(datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out: List[Any] = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ConcatDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        self.cum = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cum[-1] if self.cum else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        i = bisect.bisect_right(self.cum, idx)
        prev = self.cum[i - 1] if i else 0
        return self.datasets[i][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence, generator=None):
    """Split into non-overlapping subsets. ``lengths`` may be ints or
    fractions summing to 1 (reference: paddle.io.random_split)."""
    n = len(dataset)
    if all(0 < float(x) < 1 for x in lengths) and abs(sum(map(float, lengths)) - 1) < 1e-6:
        sizes = [int(math.floor(n * float(f))) for f in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    rng = generator or np.random.default_rng()
    perm = rng.permutation(n)
    out, ofs = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + ln].tolist()))
        ofs += ln
    return out


# ---------------------------------------------------------------------------
# samplers (reference: python/paddle/io/sampler.py, batch_sampler.py)
# ---------------------------------------------------------------------------

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __len__(self):
        return self.num_samples

    def __iter__(self):
        n = len(self.data_source)
        if self.generator is not None:
            rng = self.generator
        else:
            # deterministic under paddle_tpu.seed (reference: paddle seeds
            # the shuffle from the global generator); each epoch advances
            # the eager stream so permutations differ across epochs
            from ..core import random as prandom
            seed_val = int(jax.random.randint(
                prandom.next_key("dataloader_shuffle"), (), 0, 2**31 - 1))
            rng = np.random.default_rng(seed_val)
        if self.replacement:
            yield from rng.integers(0, n, size=self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[: self.num_samples].tolist()


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__()
        self.weights = np.asarray(weights, dtype=np.float64)
        if (self.weights < 0).any():
            raise ValueError("weights must be non-negative")
        self.num_samples = num_samples
        self.replacement = replacement

    def __len__(self):
        return self.num_samples

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        yield from rng.choice(len(p), size=self.num_samples,
                              replace=self.replacement, p=p).tolist()


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__()
        self.indices = list(indices)
        self.generator = generator

    def __len__(self):
        return len(self.indices)

    def __iter__(self):
        rng = self.generator or np.random.default_rng()
        for i in rng.permutation(len(self.indices)):
            yield self.indices[i]


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__()
        if (dataset is None) == (sampler is None):
            raise ValueError("exactly one of dataset / sampler required")
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last

    def __iter__(self):
        batch: List[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-replica batch sampler.  ``num_replicas``/``rank`` default to the
    jax *process* topology (each host loads its own shard; devices within a
    host are fed from the host's global batch by the sharded train step).
    Reference: python/paddle/io/dataloader/batch_sampler.py
    (DistributedBatchSampler over PADDLE_TRAINER_ID env)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        Sampler.__init__(self, dataset)
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.nranks = num_replicas if num_replicas is not None else jax.process_count()
        self.local_rank = rank if rank is not None else jax.process_index()
        if not 0 <= self.local_rank < self.nranks:
            raise ValueError("rank out of range")
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        n = len(dataset)
        self.num_samples = (n // self.nranks if drop_last
                            else int(math.ceil(n / self.nranks)))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int):
        """Reseed the shuffle per epoch so replicas agree on the permutation."""
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        if self.drop_last:
            indices = indices[: self.total_size]
        elif n:
            # pad by cycling: total_size - n can exceed n for tiny datasets
            indices = list(itertools.islice(itertools.cycle(indices), self.total_size))
        shard = indices[self.local_rank::self.nranks]
        assert len(shard) == self.num_samples
        batch: List[int] = []
        for idx in shard:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# ---------------------------------------------------------------------------
# collate (reference: python/paddle/io/dataloader/collate.py)
# ---------------------------------------------------------------------------

def _stack_arrays(batch):
    """np.stack with the C++ GIL-released memcpy fast path when built
    (paddle_tpu/native/pdtpu_native.cpp pdtpu_collate_stack) — lets the prefetch
    thread pool collate in parallel. collate_stack itself returns None
    when the lib is missing or the fast path doesn't apply."""
    from .. import runtime_native
    out = runtime_native.collate_stack(list(batch))
    if out is not None:
        return out
    return np.stack(batch)


def default_collate_fn(batch: Sequence[Any]):
    """Stack a list of samples into batched numpy arrays, recursing into
    dict / tuple / list sample structures."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return _stack_arrays(batch)
    if isinstance(sample, (bool, np.bool_)):  # before int: bool subclasses int
        return np.asarray(batch, dtype=np.bool_)
    if isinstance(sample, (np.floating, float)):
        return np.asarray(batch, dtype=np.float32 if isinstance(sample, float) else None)
    if isinstance(sample, (np.integer, int)):
        return np.asarray(batch, dtype=np.int64 if isinstance(sample, int) else None)
    if isinstance(sample, jax.Array):
        return np.stack([np.asarray(s) for s in batch])
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, tuple) and hasattr(sample, "_fields"):
        # namedtuple: constructor takes positional fields, not a generator
        return type(sample)(*(default_collate_fn(f) for f in zip(*batch)))
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn(fields) for fields in zip(*batch))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    try:
        return np.stack([np.asarray(s) for s in batch])
    except Exception:
        return list(batch)


# ---------------------------------------------------------------------------
# worker info (reference: python/paddle/io/dataloader/worker.py)
# ---------------------------------------------------------------------------

class WorkerInfo:
    def __init__(self, id: int, num_workers: int, seed: int, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a loader worker, describes this worker; else None."""
    return getattr(_worker_info, "info", None)


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------

class _EndOfEpoch:
    pass


_END = _EndOfEpoch()


class DataLoader:
    """Iterate a dataset as collated batches with background prefetch.

    Reference surface: python/paddle/io/dataloader/dataloader_iter.py.
    ``num_workers`` threads fetch+collate batches into a bounded queue of
    depth ``prefetch_factor * max(num_workers, 1)``; batch *order is
    preserved* regardless of worker count (the reference reorders via
    _task_infos the same way).  ``device_prefetch`` additionally moves
    finished batches to device ahead of consumption, overlapping H2D with
    the running step.
    """

    def __init__(self, dataset, batch_size=1, shuffle=False, sampler=None,
                 batch_sampler=None, num_workers=0, collate_fn=None,
                 drop_last=False, prefetch_factor=2, device_prefetch=False,
                 places=None, return_list=True, use_shared_memory=None,
                 worker_init_fn=None, timeout=0, seed: Optional[int] = None,
                 mp_context: Optional[str] = None):
        del places, return_list, timeout  # API compat
        # use_shared_memory=True selects *process* workers handing batches
        # over SharedMemory segments (the reference's default worker model;
        # GIL-free transforms). Default False: thread prefetch is enough
        # when collate is numpy-bound. Map-style datasets only.
        self.use_shared_memory = bool(use_shared_memory)
        self.mp_context = mp_context
        self.dataset = dataset
        self.num_workers = int(num_workers)
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.device_prefetch = device_prefetch
        self.worker_init_fn = worker_init_fn
        self.seed = seed
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            if batch_sampler is not None or sampler is not None:
                raise ValueError("IterableDataset does not accept samplers")
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            if batch_sampler is not None:
                if batch_size != 1 or shuffle or sampler is not None or drop_last:
                    raise ValueError("batch_sampler is mutually exclusive with "
                                     "batch_size/shuffle/sampler/drop_last")
                self.batch_sampler = batch_sampler
            else:
                if sampler is not None:
                    if shuffle:
                        raise ValueError("sampler is mutually exclusive with shuffle")
                    self.batch_sampler = BatchSampler(
                        sampler=sampler, batch_size=batch_size, drop_last=drop_last)
                else:
                    self.batch_sampler = BatchSampler(
                        dataset=dataset, shuffle=shuffle,
                        batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("DataLoader over IterableDataset has no len()")
        return len(self.batch_sampler)

    # -- iteration ---------------------------------------------------------

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        """IterableDataset path: batch each worker's stream as it goes.

        With ``num_workers > 0`` the reference contract applies: every worker
        iterates its own copy of the dataset with ``get_worker_info()`` set,
        and the dataset is responsible for sharding itself by worker id;
        batches are yielded round-robin across workers."""
        if self.num_workers > 0:
            yield from self._iter_iterable_workers()
            return
        if self.batch_size is None:
            yield from iter(self.dataset)
            return
        batch: List[Any] = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_iterable_workers(self):
        nw = self.num_workers
        out_q: "queue.Queue" = queue.Queue(self.prefetch_factor * nw)
        stop = threading.Event()

        def worker(wid: int):
            _worker_info.info = WorkerInfo(wid, nw, (self.seed or 0) + wid, self.dataset)
            try:
                if self.worker_init_fn is not None:
                    self.worker_init_fn(wid)
                batch: List[Any] = []
                for sample in self.dataset:
                    if stop.is_set():
                        return
                    if self.batch_size is None:
                        out_q.put((wid, sample))
                        continue
                    batch.append(sample)
                    if len(batch) == self.batch_size:
                        out_q.put((wid, self.collate_fn(batch)))
                        batch = []
                if batch and not self.drop_last:
                    out_q.put((wid, self.collate_fn(batch)))
            except BaseException as e:
                out_q.put((wid, e))
            finally:
                out_q.put((wid, _END))
                _worker_info.info = None

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(nw)]
        for t in threads:
            t.start()
        live = nw
        try:
            while live:
                wid, item = out_q.get()
                if item is _END:
                    live -= 1
                elif isinstance(item, BaseException):
                    raise item
                else:
                    yield item
        finally:
            stop.set()
            while not out_q.empty():  # unblock producers stuck on put()
                try:
                    out_q.get_nowait()
                except queue.Empty:
                    break
            for t in threads:
                t.join(timeout=1.0)

    def _iter_workers(self):
        """Ordered thread-pool prefetch over the batch sampler."""
        nw = self.num_workers
        batches = list(self.batch_sampler)
        out_slots: dict = {}
        out_lock = threading.Condition()
        task_q: "queue.Queue" = queue.Queue()
        for i, idxs in enumerate(batches):
            task_q.put((i, idxs))
        stop = threading.Event()
        max_ahead = self.prefetch_factor * nw

        next_to_yield = [0]

        fatal: List[BaseException] = []  # worker-init failures: always raised

        def worker(wid: int):
            _worker_info.info = WorkerInfo(wid, nw, (self.seed or 0) + wid, self.dataset)
            try:
                if self.worker_init_fn is not None:
                    try:
                        self.worker_init_fn(wid)
                    except BaseException as e:
                        with out_lock:
                            fatal.append(e)
                            out_lock.notify_all()
                        return
                while not stop.is_set():
                    try:
                        i, idxs = task_q.get_nowait()
                    except queue.Empty:
                        return
                    # throttle: don't run unboundedly ahead of the consumer
                    with out_lock:
                        while (not stop.is_set()
                               and i - next_to_yield[0] > max_ahead):
                            out_lock.wait(0.05)
                        if stop.is_set():
                            return
                    try:
                        result = self._fetch(idxs)
                    except BaseException as e:  # propagate to consumer
                        result = e
                    with out_lock:
                        out_slots[i] = result
                        out_lock.notify_all()
            finally:
                _worker_info.info = None

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(nw)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                with out_lock:
                    while i not in out_slots and not fatal:
                        out_lock.wait()
                    if fatal:
                        raise fatal[0]
                    result = out_slots.pop(i)
                    next_to_yield[0] = i + 1
                    out_lock.notify_all()
                if isinstance(result, BaseException):
                    raise result
                yield result
        finally:
            stop.set()
            with out_lock:
                out_lock.notify_all()
            for t in threads:
                t.join(timeout=1.0)

    def _iter_sync(self):
        for idxs in self.batch_sampler:
            yield self._fetch(idxs)

    def _iter_process_workers(self):
        from .process_workers import ProcessPoolIter
        pool = ProcessPoolIter(self.dataset, list(self.batch_sampler),
                               self.collate_fn, self.num_workers,
                               prefetch_factor=self.prefetch_factor,
                               worker_init_fn=self.worker_init_fn,
                               seed=self.seed or 0,
                               mp_context=self.mp_context)
        return iter(pool)

    def __iter__(self):
        if self._iterable:
            if self.use_shared_memory and self.num_workers > 0:
                raise ValueError(
                    "use_shared_memory process workers need a map-style "
                    "dataset (IterableDataset streams per worker thread)")
            it = self._iter_iterable()
        elif self.num_workers > 0 and self.use_shared_memory:
            it = self._iter_process_workers()
        elif self.num_workers > 0:
            it = self._iter_workers()
        else:
            it = self._iter_sync()
        if self.device_prefetch:
            it = _device_prefetch(it)
        return it


def _device_prefetch(it: Iterator, depth: int = 2):
    """Keep ``depth`` batches resident on device ahead of the consumer,
    overlapping host→device transfer with compute (jax transfers are async)."""
    def put(leaf):
        # leave non-numeric leaves (e.g. list-of-str fields) on host
        return jax.device_put(leaf) if isinstance(leaf, (np.ndarray, jax.Array)) else leaf

    buf: List[Any] = []
    for batch in it:
        buf.append(jax.tree_util.tree_map(put, batch))
        if len(buf) > depth:
            yield buf.pop(0)
    yield from buf


def default_convert_fn(batch):
    """Reference: paddle.io.dataloader.collate.default_convert_fn —
    convert leaves to arrays WITHOUT adding a batch dim (the no-batch
    collate used when batch_size=None)."""
    import numpy as _np

    import jax.numpy as _jnp
    if isinstance(batch, tuple) and hasattr(batch, "_fields"):
        # namedtuple: constructor takes positional fields, not a generator
        return type(batch)(*(default_convert_fn(b) for b in batch))
    if isinstance(batch, (list, tuple)):
        return type(batch)(default_convert_fn(b) for b in batch)
    if isinstance(batch, dict):
        return {k: default_convert_fn(v) for k, v in batch.items()}
    if isinstance(batch, (_np.ndarray, int, float)):
        return _jnp.asarray(batch)
    return batch
