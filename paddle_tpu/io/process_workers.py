"""Multiprocess DataLoader workers over shared memory.

Reference: python/paddle/io/dataloader/worker.py + the C++ shared-memory
queue (``use_shared_memory=True`` default in paddle.io.DataLoader,
SURVEY §2.6 "Data pipeline"): worker *processes* run dataset+collate and
hand batches to the trainer through shared memory, bypassing both the
GIL and pipe serialization.

TPU redesign: the accelerator does not read host queues — batches end as
``jax.device_put`` H2D copies — so the worker side stays pure
numpy/CPython. Worker processes matter on TPU for the same reason as on
GPU: heavy Python transforms (tokenization, image decode) are GIL-bound
in threads. Each finished batch is packed into ONE SharedMemory segment
(all array leaves concatenated, page-aligned offsets); the parent maps
zero-copy numpy views and unlinks the segment two batches later (the
views' lifetime window a training step actually uses).

Map-style datasets only — the iterable path keeps thread workers (its
per-worker streaming contract has no index protocol to ship across
processes).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import queue as _queue
import threading
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional

import numpy as np

_END = "__end__"
_ALIGN = 128


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _pack_batch(batch) -> tuple:
    """Flatten a batch pytree; numpy leaves go to one shm segment."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(batch)
    metas: List[Any] = []
    total = 0
    for leaf in leaves:
        if isinstance(leaf, np.ndarray):
            off = _align(total)
            metas.append(("arr", off, leaf.dtype.str, leaf.shape))
            total = off + leaf.nbytes
        else:
            metas.append(("obj", leaf))
    shm_name = None
    if total:
        seg = shared_memory.SharedMemory(create=True, size=max(total, 1))
        for meta, leaf in zip(metas, leaves):
            if meta[0] == "arr":
                _, off, dstr, shape = meta
                dst = np.ndarray(shape, dtype=np.dtype(dstr),
                                 buffer=seg.buf, offset=off)
                dst[...] = leaf
        shm_name = seg.name
        # ownership moves to the consumer (which unlinks): silence this
        # process's resource_tracker so worker exit doesn't double-free
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:
            pass
        seg.close()  # worker's mapping; the segment itself lives on
    return shm_name, metas, pickle.dumps(treedef)


def _unpack_batch(shm_name, metas, treedef_bytes):
    """Copy arrays out of the segment and retire it immediately.

    The copy is deliberate: numpy does not pin the SharedMemory mmap for
    a view's lifetime (the mapping dies with the SharedMemory object, so
    zero-copy views dangle once the segment is retired — observed as a
    segfault on any consumer that retains batches). One parent-side
    memcpy per batch is the reference's behaviour too (its C++ shm queue
    copies into the reader's tensor) and is still far cheaper than pipe
    pickling, which serializes AND copies twice."""
    import jax
    treedef = pickle.loads(treedef_bytes)
    seg = shared_memory.SharedMemory(name=shm_name) if shm_name else None
    leaves = []
    for meta in metas:
        if meta[0] == "arr":
            _, off, dstr, shape = meta
            if seg is None:  # every leaf zero-size → no segment was made
                leaves.append(np.zeros(shape, dtype=np.dtype(dstr)))
            else:
                view = np.ndarray(shape, dtype=np.dtype(dstr),
                                  buffer=seg.buf, offset=off)
                leaves.append(view.copy())
        else:
            leaves.append(meta[1])
    if seg is not None:
        _unlink_quiet(seg)
        seg.close()
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _unlink_quiet(seg):
    # no resource_tracker.unregister here: the creating worker already
    # unregistered at pack time (and under fork both sides share one
    # tracker process — a second unregister makes the tracker print
    # KeyError tracebacks for every batch)
    try:
        seg.unlink()
    except FileNotFoundError:
        pass


def _worker_main(dataset, collate_fn, worker_init_fn, wid, nw, seed,
                 task_q, result_q):
    # late import keeps jax out of the child's critical path; workers never
    # touch the device (forked TPU handles are unsafe, same as CUDA in the
    # reference's workers)
    from . import WorkerInfo, _worker_info
    _worker_info.info = WorkerInfo(wid, nw, seed + wid, dataset)
    try:
        if worker_init_fn is not None:
            try:
                worker_init_fn(wid)
            except BaseException as e:
                # -2 = init failure: parent raises the real exception
                # (parity with the thread path's `fatal` list)
                result_q.put((-2, "err", pickle.dumps(e)))
                return
        while True:
            task = task_q.get()
            if task is None:
                return
            i, idxs = task
            try:
                samples = [dataset[j] for j in idxs]
                payload = _pack_batch(collate_fn(samples))
                result_q.put((i, "ok", payload))
            except BaseException as e:  # propagate to the consumer
                try:
                    result_q.put((i, "err", pickle.dumps(e)))
                except Exception:
                    result_q.put((i, "err", pickle.dumps(
                        RuntimeError(f"worker {wid}: {type(e).__name__}: {e}"))))
    finally:
        result_q.put((-1, _END, wid))


class ProcessPoolIter:
    """Ordered multiprocess prefetch over a batch sampler (the process
    analogue of DataLoader._iter_workers' ordered thread pool)."""

    def __init__(self, dataset, batches, collate_fn, num_workers,
                 prefetch_factor=2, worker_init_fn=None, seed=0,
                 mp_context: Optional[str] = None):
        self.batches = list(batches)
        self.nw = num_workers
        self.max_ahead = max(1, prefetch_factor) * num_workers
        ctx = mp.get_context(mp_context or "fork")
        self.task_q = ctx.Queue()
        self.result_q = ctx.Queue()
        self.procs = [
            ctx.Process(target=_worker_main,
                        args=(dataset, collate_fn, worker_init_fn, w,
                              num_workers, seed, self.task_q, self.result_q),
                        daemon=True)
            for w in range(num_workers)]
        for p in self.procs:
            p.start()
        self._next_task = 0
        self._done = False
        # prime the task queue up to the prefetch window
        while self._next_task < min(self.max_ahead, len(self.batches)):
            self._submit()

    def _submit(self):
        self.task_q.put((self._next_task, self.batches[self._next_task]))
        self._next_task += 1

    def __iter__(self):
        slots: Dict[int, Any] = {}
        try:
            for i in range(len(self.batches)):
                while i not in slots:
                    try:
                        j, status, payload = self.result_q.get(timeout=5.0)
                    except _queue.Empty:
                        dead = [w for w, p in enumerate(self.procs)
                                if not p.is_alive()]
                        if dead:  # hard death (OOM-kill/segfault): no
                            # Python-level sentinel ever arrives — raise
                            # instead of hanging the training loop
                            raise RuntimeError(
                                f"DataLoader worker(s) {dead} died "
                                f"(exitcodes "
                                f"{[self.procs[w].exitcode for w in dead]})")
                        continue
                    if status == _END:
                        raise RuntimeError(
                            f"DataLoader worker {payload} exited early")
                    if status == "err":
                        raise pickle.loads(payload)
                    slots[j] = payload
                batch = _unpack_batch(*slots.pop(i))
                if self._next_task < len(self.batches):
                    self._submit()
                yield batch
        finally:
            # map-then-unlink any fetched-but-unyielded segments
            for payload in slots.values():
                _unpack_batch(*payload)
            self.close()

    def close(self):
        if self._done:
            return
        self._done = True
        # cancel queued work so workers see the sentinel promptly
        while True:
            try:
                self.task_q.get_nowait()
            except (_queue.Empty, OSError, EOFError):
                break
        for _ in self.procs:
            self.task_q.put(None)
        for p in self.procs:
            # generous join: a worker mid-batch must finish and send its
            # segment name or the segment can never be unlinked (terminate
            # between shm create and send is the one unavoidable leak)
            p.join(timeout=10.0)
            if p.is_alive():
                p.terminate()
        # drain late results so their segments don't leak; use a short
        # timeout, not get_nowait — the queue feeder may still be flushing
        while True:
            try:
                j, status, payload = self.result_q.get(timeout=0.25)
                if status == "ok" and payload[0]:
                    try:
                        seg = shared_memory.SharedMemory(name=payload[0])
                        _unlink_quiet(seg)
                        seg.close()
                    except FileNotFoundError:
                        pass
            except (_queue.Empty, OSError, EOFError):
                break

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
