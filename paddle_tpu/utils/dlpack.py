"""``paddle.utils.dlpack`` parity: zero-copy tensor interchange.

Reference: python/paddle/utils/dlpack.py (to_dlpack/from_dlpack).

jax speaks DLPack natively; these wrappers keep the reference call
shapes and accept any DLPack-exporting object (torch tensors included),
which is the practical CPU-side interop path for mixed pipelines.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """jax array → DLPack capsule (consumable by torch/numpy/cupy)."""
    return jnp.asarray(x).__dlpack__()


def from_dlpack(capsule_or_tensor):
    """DLPack capsule or any __dlpack__-exporting object → jax array."""
    return jnp.from_dlpack(capsule_or_tensor)
