"""``paddle.utils`` parity: try_import, run_check, unique_name, deprecated,
cpp_extension pointer.

Reference: python/paddle/utils/ (install_check.run_check, unique_name.py,
deprecated decorator, cpp_extension/ for custom-op builds).
"""

from __future__ import annotations

import functools
import importlib
import threading
import warnings
from typing import Optional

from . import cpp_extension  # noqa: F401
from . import dlpack  # noqa: F401

__all__ = ["try_import", "run_check", "unique_name", "deprecated",
           "cpp_extension", "download",
           "require_version"]

from . import download  # noqa: E402,F401


def try_import(module_name: str, err_msg: Optional[str] = None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed "
                       f"(this image is frozen — gate the feature instead)")


def run_check():
    """Device sanity check (reference: paddle.utils.run_check prints GPU
    status; here: jax backend + a tiny compiled matmul on every device)."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((8, 8))
    y = jax.jit(lambda a: a @ a)(x)
    y.block_until_ready()
    print(f"paddle_tpu is installed successfully! "
          f"{len(devs)} {devs[0].platform} device(s) available; "
          f"compiled matmul OK (sum={float(y.sum()):.0f}).")
    return True


class _UniqueName:
    """paddle.utils.unique_name: generate/guard/switch."""

    def __init__(self):
        self._tls = threading.local()

    def _counters(self):
        if not hasattr(self._tls, "c"):
            self._tls.c = {}
        return self._tls.c

    def generate(self, key: str) -> str:
        c = self._counters()
        n = c.get(key, 0)
        c[key] = n + 1
        return f"{key}_{n}"

    def switch(self, new_counters=None):
        old = self._counters()
        self._tls.c = dict(new_counters or {})
        return old

    class guard:
        def __init__(self, new_generator=None):
            self.new = new_generator

        def __enter__(self):
            self.old = unique_name.switch({})
            return self

        def __exit__(self, *exc):
            unique_name.switch(self.old)
            return False


unique_name = _UniqueName()


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Decorator emitting a DeprecationWarning on first call (reference
    paddle.utils.deprecated)."""

    def deco(fn):
        warned = []

        @functools.wraps(fn)
        def wrapper(*a, **k):
            if not warned:
                warned.append(1)
                msg = f"{fn.__name__} is deprecated"
                if since:
                    msg += f" since {since}"
                if update_to:
                    msg += f"; use {update_to} instead"
                if reason:
                    msg += f" ({reason})"
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **k)

        return wrapper

    return deco


def require_version(min_version: str, max_version: Optional[str] = None):
    from .. import __version__

    def parse(v):
        return tuple(int(x) for x in v.split(".")[:3])

    cur = parse(__version__)
    if parse(min_version) > cur or (max_version and parse(max_version) < cur):
        raise RuntimeError(
            f"paddle_tpu {__version__} outside required "
            f"[{min_version}, {max_version or '∞'}]")
    return True
