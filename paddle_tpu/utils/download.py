"""paddle_tpu.utils.download — weight-cache resolution.

Reference: python/paddle/utils/download.py (get_weights_path_from_url /
get_path_from_url over a ~/.cache dir).  This environment has zero
network egress, so resolution is cache-only: a URL whose file is already
in the cache (placed there by the operator) resolves; anything else
raises with the cache path to populate.
"""

from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url", "get_path_from_url", "WEIGHTS_HOME"]

WEIGHTS_HOME = os.path.expanduser(
    os.environ.get("PDTPU_WEIGHTS_HOME", "~/.cache/paddle_tpu/weights"))


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    root = root_dir or WEIGHTS_HOME
    fname = url.split("/")[-1].split("?")[0]
    path = os.path.join(root, fname)
    if os.path.exists(path):
        return path
    raise FileNotFoundError(
        f"download is disabled (zero-egress environment); place the file "
        f"for {url!r} at {path!r} and retry")


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
