"""``paddle.utils.cpp_extension`` parity: runtime C++ custom-op builds.

Reference: python/paddle/utils/cpp_extension/ (setup/load compile custom
operators against libpaddle with nvcc/gcc).

TPU redesign: custom device code is Pallas (Python), so the native
extension path targets the HOST runtime — the same role as the rest of
``paddle_tpu/native/``: data-loader transforms, tokenizers, IO. ``load()`` compiles
C/C++ sources with the system toolchain into a shared object (cached by
source hash) and returns a ``ctypes.CDLL``; declare signatures on the
returned handle. No Python.h needed — plain ``extern "C"`` functions,
the ctypes pattern used by ``paddle_tpu.runtime_native``.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional, Sequence

__all__ = ["load", "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PDTPU_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "pdtpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str, sources: Sequence[str], extra_cflags: Sequence[str] = (),
         extra_ldflags: Sequence[str] = (), verbose: bool = False,
         build_directory: Optional[str] = None) -> ctypes.CDLL:
    """Compile ``sources`` (paths or inline code strings containing a
    newline) into ``lib<name>.so`` and dlopen it. Rebuilds only when the
    combined source/flags hash changes."""
    build_dir = build_directory or get_build_directory()
    texts = []
    for s in sources:
        if "\n" in s:  # inline source string
            texts.append(s)
        else:
            with open(s) as f:
                texts.append(f.read())
    h = hashlib.sha256(
        ("\0".join(texts) + repr(tuple(extra_cflags))
         + repr(tuple(extra_ldflags))).encode()).hexdigest()[:16]
    lib_path = os.path.join(build_dir, f"lib{name}_{h}.so")
    if not os.path.exists(lib_path):
        compile_srcs = []
        for i, s in enumerate(sources):
            if "\n" in s:  # materialize inline source
                p = os.path.join(build_dir, f"{name}_{h}_{i}.cc")
                with open(p, "w") as f:
                    f.write(s)
                compile_srcs.append(p)
            else:
                compile_srcs.append(s)
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *extra_cflags, *compile_srcs, "-o", lib_path, *extra_ldflags]
        if verbose:
            print("cpp_extension:", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=not verbose)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"cpp_extension build failed:\n"
                f"{(e.stderr or b'').decode(errors='replace')}") from e
    return ctypes.CDLL(lib_path)
