"""``paddle.static`` facade — Program/data/Executor on top of XLA.

Reference: python/paddle/static/ (Program, program_guard, data, Executor,
CompiledProgram) over the C++ ProgramDesc/InterpreterCore stack (SURVEY
§2.3). The reference builds a protobuf op graph and interprets it; here a
``Program`` records a lazy expression graph of ``Var`` nodes and
``Executor.run`` JIT-compiles it with XLA (cached per feed signature) — the
InterpreterCore/stream-scheduling machinery is exactly what XLA replaces
(SURVEY §7.3).

Since round 3, the dynamic ``paddle_tpu.ops`` / ``nn.functional``
callables ALSO accept ``Var`` placeholders directly (``enable_var_dispatch``
wraps them at import: a call with Var arguments records a graph node
instead of executing) — reference static-graph code can call ``paddle.*``
ops unchanged, like the reference's own in-graph dispatch.
``@paddle_tpu.jit.to_static`` remains the primary graph-capture path, as
in the reference's 3.0 dynamic-first design.
"""

from __future__ import annotations

import contextlib as _contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "InputSpec", "Executor",
           "CompiledProgram", "Var", "apply", "nn",
           "gradients", "append_backward", "Scope", "global_scope",
           "scope_guard", "save_inference_model", "load_inference_model"]


class Var:
    """Symbolic node in a Program's expression graph."""

    _next_id = [0]
    _any_created = [False]   # cheap eager-path guard for _wrap_for_vars

    def __init__(self, program: "Program", op: Optional[Tuple] = None,
                 shape=None, dtype=None, name=None):
        Var._any_created[0] = True
        self.program = program
        self.op = op          # None for placeholders, else (fn, args, kwargs)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name or f"var_{Var._next_id[0]}"
        Var._next_id[0] += 1
        program._vars[self.name] = self

    # -- graph building ----------------------------------------------------

    def _wrap(self, fn, *args, **kwargs):
        return Var(self.program, op=(fn, args, kwargs))

    def __add__(self, o): return self._wrap(jnp.add, self, o)
    def __radd__(self, o): return self._wrap(jnp.add, o, self)
    def __sub__(self, o): return self._wrap(jnp.subtract, self, o)
    def __rsub__(self, o): return self._wrap(jnp.subtract, o, self)
    def __mul__(self, o): return self._wrap(jnp.multiply, self, o)
    def __rmul__(self, o): return self._wrap(jnp.multiply, o, self)
    def __truediv__(self, o): return self._wrap(jnp.divide, self, o)
    def __rtruediv__(self, o): return self._wrap(jnp.divide, o, self)
    def __pow__(self, o): return self._wrap(jnp.power, self, o)
    def __neg__(self): return self._wrap(jnp.negative, self)
    def __matmul__(self, o): return self._wrap(jnp.matmul, self, o)
    def __getitem__(self, idx): return self._wrap(lambda x, i: x[i], self, idx)
    def __lt__(self, o): return self._wrap(jnp.less, self, o)
    def __le__(self, o): return self._wrap(jnp.less_equal, self, o)
    def __gt__(self, o): return self._wrap(jnp.greater, self, o)
    def __ge__(self, o): return self._wrap(jnp.greater_equal, self, o)

    def astype(self, dtype): return self._wrap(lambda x: x.astype(dtype), self)
    def reshape(self, shape): return self._wrap(jnp.reshape, self, shape)
    def transpose(self, perm): return self._wrap(jnp.transpose, self, perm)
    def sum(self, axis=None, keepdim=False):
        return self._wrap(lambda x: jnp.sum(x, axis=axis, keepdims=keepdim), self)
    def mean(self, axis=None, keepdim=False):
        return self._wrap(lambda x: jnp.mean(x, axis=axis, keepdims=keepdim), self)
    def max(self, axis=None, keepdim=False):
        return self._wrap(lambda x: jnp.max(x, axis=axis, keepdims=keepdim), self)
    def min(self, axis=None, keepdim=False):
        return self._wrap(lambda x: jnp.min(x, axis=axis, keepdims=keepdim), self)
    def matmul(self, o): return self.__matmul__(o)
    def exp(self): return self._wrap(jnp.exp, self)
    def log(self): return self._wrap(jnp.log, self)
    def tanh(self): return self._wrap(jnp.tanh, self)
    def sqrt(self): return self._wrap(jnp.sqrt, self)
    def abs(self): return self._wrap(jnp.abs, self)

    def __repr__(self):
        kind = "data" if self.op is None else "op"
        return f"Var({self.name}, {kind}, shape={self.shape})"


def apply(fn: Callable, *args, **kwargs) -> Var:
    """Apply any jnp-compatible function to Vars/constants symbolically.
    Shares the Var discovery (one nesting level of lists/tuples) with the
    ``enable_var_dispatch`` wrapping below."""
    prog = _find_program(args) or _find_program(tuple(kwargs.values()))
    if prog is None:
        raise ValueError("apply() needs at least one Var argument")
    return Var(prog, op=(fn, args, kwargs))


class Program:
    """Records placeholders + the lazy op graph hanging off them."""

    def __init__(self):
        self._vars: Dict[str, Var] = {}
        self._datas: List[Var] = []
        self._cache: Dict[Any, Any] = {}

    def data(self, name, shape, dtype="float32") -> Var:
        v = Var(self, op=None, shape=shape, dtype=dtype, name=name)
        self._datas.append(v)
        return v

    def _eval(self, fetch: Sequence[Var], feed: Dict[str, np.ndarray]):
        """Compile (cached by feed shapes/dtypes) and run the graph."""
        feed_names = tuple(v.name for v in self._datas if v.name in feed)
        sig = (tuple((n, feed[n].shape, str(np.asarray(feed[n]).dtype))
                     for n in feed_names),
               tuple(v.name for v in fetch))
        fn = self._cache.get(sig)
        if fn is None:
            def run_graph(*feed_vals):
                env = dict(zip(feed_names, feed_vals))
                return tuple(_eval_var(v, env) for v in fetch)

            fn = jax.jit(run_graph)
            self._cache[sig] = fn
        return fn(*[jnp.asarray(feed[n]) for n in feed_names])

    def global_block(self):
        return self

    @property
    def vars(self):
        return self._vars


_tls = threading.local()


def _stack() -> List[Program]:
    if not hasattr(_tls, "stack"):
        _tls.stack = [Program()]
    return _tls.stack


def default_main_program() -> Program:
    return _stack()[-1]


def default_startup_program() -> Program:
    # parameter init happens eagerly in this design; the startup program is
    # an empty Program kept for API parity
    if not hasattr(_tls, "startup"):
        _tls.startup = Program()
    return _tls.startup


class program_guard:
    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _stack().append(self.main)
        return self.main

    def __exit__(self, *exc):
        _stack().pop()
        return False


def data(name: str, shape, dtype="float32") -> Var:
    return default_main_program().data(name, shape, dtype)


class InputSpec:
    """Re-export of jit.InputSpec at the reference's static location."""

    def __new__(cls, shape, dtype="float32", name=None):
        from ..jit import InputSpec as _IS
        return _IS(shape, dtype=dtype, name=name)


class Executor:
    """``paddle.static.Executor`` parity: run(program, feed, fetch_list)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        single = isinstance(fetch_list, Var)
        if single:
            fetch_list = [fetch_list]
        outs = program._eval(fetch_list, feed)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs[0] if single else list(outs)


class CompiledProgram:
    """Reference CompiledProgram accepted alias — XLA always compiles."""

    def __init__(self, program: Program, build_strategy=None):
        self.program = program


# ``paddle.static.nn`` is a real submodule (reference:
# python/paddle/static/nn/) — layer builders that create parameters
# eagerly and record ops symbolically, plus the (padded, length) sequence
# op suite.  Imported lazily via __getattr__ below to avoid a circular
# import (nn.py needs jit.control_flow which needs this module).


def __getattr__(name):
    if name == "nn":
        import importlib
        mod = importlib.import_module(".nn", __name__)
        globals()["nn"] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu.static' has no attribute "
                         f"{name!r}")


# -- mode toggles (reference: paddle.enable_static/disable_static,
# paddle.in_dynamic_mode — base/framework.py). Dygraph is the default and
# the documented path; static mode routes nn/ops through the Program
# facade for call-shape compatibility.
_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_static_mode() -> bool:
    return _static_mode[0]


def in_dynamic_mode() -> bool:
    return not _static_mode[0]


# -- static autodiff (reference: paddle.static.gradients / append_backward
# over the Program; here jax.grad of the recorded Var DAG) ------------------

def _find_program(items) -> Optional["Program"]:
    for a in items:
        if isinstance(a, Var):
            return a.program
        if isinstance(a, (list, tuple)):
            for b in a:
                if isinstance(b, Var):
                    return b.program
    return None


def _wrap_for_vars(fn):
    """Static-graph interception: calling a dynamic op with Var arguments
    records a graph node instead of executing — the same ``paddle.*``
    function works in both modes, like the reference's in-graph op
    dispatch (python/paddle/base/framework.py in_dygraph_mode branches)."""
    import functools as _functools

    @_functools.wraps(fn)
    def wrapper(*args, **kwargs):
        # fast path: no Var has ever been constructed in this process, so
        # the nested isinstance scan cannot find one — eager calls pay one
        # list-index check instead of a per-arg walk
        if not Var._any_created[0]:
            return fn(*args, **kwargs)
        prog = _find_program(args) or _find_program(tuple(kwargs.values()))
        if prog is None:
            return fn(*args, **kwargs)
        return Var(prog, op=(fn, args, kwargs))

    wrapper._var_dispatch = True
    return wrapper


def _wrappable(f) -> bool:
    import types as _types
    return (callable(f) and not isinstance(f, type)
            and not isinstance(f, _types.ModuleType)
            # typing constructs (Optional, Union, ...) are callable but
            # must never be rebound to functions
            and getattr(f, "__module__", "") != "typing"
            and not getattr(f, "_var_dispatch", False))


def enable_var_dispatch(module, names=None) -> int:
    """Wrap a module's public functions so they accept static ``Var``s
    (lazily recorded) as well as real arrays.  Returns the wrap count.
    Wraps plain functions, jnp ufunc objects, jax custom_jvp/custom_vjp
    callables and partials — everything except classes and modules.

    Caveat: this rebinds MODULE ATTRIBUTES, so call sites that did
    ``from module import fn`` *before* wrapping hold the unwrapped
    function and bypass Var dispatch (they still work eagerly — a Var
    argument there raises).  Intra-package code therefore keeps such
    imports module-qualified (``F.relu``, ``ops.concat``); do the same
    in ported static-graph code, as ``import paddle`` users already do."""
    count = 0
    for n in (names if names is not None
              else getattr(module, "__all__", None) or dir(module)):
        if n.startswith("_"):
            continue
        f = getattr(module, n, None)
        if _wrappable(f):
            setattr(module, n, _wrap_for_vars(f))
            count += 1
    return count


def enable_var_dispatch_class(cls) -> int:
    """Same, for staticmethod-namespace classes (``paddle_tpu.linalg`` /
    ``paddle_tpu.fft``)."""
    count = 0
    for n in list(vars(cls)):
        if n.startswith("_"):
            continue
        f = getattr(cls, n, None)
        if _wrappable(f):
            setattr(cls, n, staticmethod(_wrap_for_vars(f)))
            count += 1
    return count


def _eval_var(node, env):
    """THE evaluator over the recorded op DAG — used by Program._eval,
    gradients() closures and save_inference_model (one copy to fix)."""
    if isinstance(node, Var):
        if node.name in env:
            return env[node.name]
        if node.op is None:
            raise KeyError(f"placeholder {node.name!r} not fed")
        f, args, kwargs = node.op
        val = f(*[_eval_var(a, env) for a in args],
                **{k: _eval_var(v, env) for k, v in kwargs.items()})
        env[node.name] = val
        return val
    if isinstance(node, (list, tuple)):
        return type(node)(_eval_var(x, env) for x in node)
    return node


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d(sum(targets))/d(input) as new graph Vars (reference:
    paddle.static.gradients — there a backward-op pass over the Program;
    here jax.grad of the DAG evaluation, compiled with the rest of the
    program)."""
    targets = [targets] if isinstance(targets, Var) else list(targets)
    inputs = [inputs] if isinstance(inputs, Var) else list(inputs)
    if target_gradients is not None:
        raise NotImplementedError(
            "target_gradients: seed the cotangent by scaling the target "
            "instead")
    prog = targets[0].program
    datas = tuple(prog._datas)

    def make_grad(inp):
        def grad_op(*data_vals):
            base = {d.name: v for d, v in zip(datas, data_vals)}
            # linearization point: feed value for placeholders, else the
            # intermediate's current value (differentiating w.r.t. an
            # intermediate treats it as an independent leaf — reference
            # gradients() supports both)
            x0 = base[inp.name] if inp.name in base else \
                _eval_var(inp, dict(base))

            def scalar_of(x):
                env = dict(base)
                env[inp.name] = x
                total = 0.0
                for t in targets:
                    total = total + jnp.sum(_eval_var(t, dict(env)))
                return total

            return jax.grad(scalar_of)(x0)
        grad_op.__name__ = f"grad_{inp.name}"
        return grad_op

    return [apply(make_grad(i), *datas) for i in inputs]


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Reference: paddle.static.append_backward → [(var, grad_var)].
    The facade's differentiable leaves are the program's data placeholders
    (parameters live eagerly on Layers in this design — documented
    deviation; use jit.TrainStep for parameter training)."""
    prog = loss.program
    leaves = parameter_list if parameter_list is not None else \
        list(prog._datas)
    grads = gradients(loss, leaves, no_grad_set=no_grad_set)
    return list(zip(leaves, grads))


# -- scopes (reference: paddle.static.global_scope/scope_guard over the C++
# Scope tree; a plain name→value mapping here) ------------------------------

class Scope:
    def __init__(self):
        self._vars: Dict[str, Any] = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def set_var(self, name, value):
        self._vars[name] = value

    def find_var(self, name):
        return self._vars.get(name)


_scope_stack: List[Scope] = [Scope()]


def global_scope() -> Scope:
    return _scope_stack[-1]


class scope_guard:
    def __init__(self, scope: Scope):
        self.scope = scope

    def __enter__(self):
        _scope_stack.append(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _scope_stack.pop()
        return False


# -- inference model save/load (reference: paddle.static.save/
# load_inference_model → __model__ + params; here a StableHLO AOT artifact
# via jit.save) -------------------------------------------------------------

def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         **kwargs):
    from .. import jit as _jit
    feed_vars = [feed_vars] if isinstance(feed_vars, Var) else list(feed_vars)
    fetch_vars = [fetch_vars] if isinstance(fetch_vars, Var) \
        else list(fetch_vars)
    for v in feed_vars:
        if any(d in (None, -1) for d in v.shape):
            raise ValueError(
                f"feed var {v.name!r} has dynamic dims {v.shape}; AOT "
                "export needs static shapes")
    names = [v.name for v in feed_vars]

    def fn(*feed_vals):
        env = dict(zip(names, feed_vals))
        return tuple(_eval_var(v, env) for v in fetch_vars)

    from ..core import convert_dtype
    examples = [jnp.zeros(tuple(v.shape), convert_dtype(v.dtype))
                for v in feed_vars]
    _jit.save(fn, path_prefix, *examples)
    import json
    with open(path_prefix + ".feeds.json", "w") as f:
        json.dump({"feed_names": names,
                   "n_fetch": len(fetch_vars)}, f)


class _LoadedInference:
    """Program stand-in returned by load_inference_model; Executor.run
    works on it with the returned fetch targets."""

    def __init__(self, fn, feed_names, n_fetch):
        self._fn = fn
        self.feed_names = feed_names
        self.n_fetch = n_fetch

    def _eval(self, fetch, feed):
        outs = self._fn(*[jnp.asarray(feed[n]) for n in self.feed_names])
        return tuple(outs[i] for i in fetch)


def load_inference_model(path_prefix: str, executor, **kwargs):
    """→ [program, feed_target_names, fetch_targets] (reference shape)."""
    import json

    from .. import jit as _jit
    fn = _jit.load(path_prefix)
    with open(path_prefix + ".feeds.json") as f:
        meta = json.load(f)
    prog = _LoadedInference(fn, meta["feed_names"], meta["n_fetch"])
    return [prog, list(meta["feed_names"]), list(range(meta["n_fetch"]))]


@_contextlib.contextmanager
def name_scope(prefix=None):
    """Reference: paddle.static.name_scope — names ops for debugging; maps
    to jax.named_scope (shows up in HLO op metadata / profiles)."""
    import jax as _jax
    with _jax.named_scope(prefix or "scope"):
        yield


def cpu_places(device_count=None):
    """Reference: paddle.static.cpu_places."""
    from ..device import CPUPlace
    import os as _os
    n = device_count or int(_os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


# ---------------------------------------------------------------------------
# round-4 static tail (reference: python/paddle/static/__init__.py surface)
# ---------------------------------------------------------------------------

Variable = Var  # reference name for graph variables


def cuda_places(device_ids=None):
    """Reference: paddle.static.cuda_places — accelerator places; the
    accelerator here is the TPU."""
    from ..device import TPUPlace, device_count
    ids = device_ids if device_ids is not None else range(device_count())
    return [TPUPlace(i) for i in ids]


xpu_places = cuda_places
npu_places = cuda_places


@_contextlib.contextmanager
def device_guard(device=None):
    """Reference: paddle.static.device_guard — pin ops to a device.  Under
    XLA, placement is whole-computation (jit backend) not per-op; 'cpu'
    guards map to jax.default_device(cpu) which IS per-region."""
    import jax as _jax
    if device and str(device).startswith("cpu"):
        with _jax.default_device(_jax.devices("cpu")[0]):
            yield
    else:
        yield  # accelerator placement is the jit default


@_contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    """Reference: paddle.static.ipu_shard_guard — IPU pipeline-stage
    annotation.  No IPUs here: accepted and ignored so ported code runs;
    use distributed.pipeline for real pipeline parallelism."""
    yield


def save(program, model_path, protocol=4):
    """Reference: paddle.static.save — persist program parameter state."""
    from .. import ckpt as _ckpt
    _ckpt.save(save_program_state(program), model_path + ".pdparams")


def load(program, model_path, executor=None, var_list=None):
    """Reference: paddle.static.load — restore parameters saved by save()."""
    from .. import ckpt as _ckpt
    set_program_state(program, _ckpt.load(model_path + ".pdparams"))


def save_program_state(program=None):
    """Snapshot {name: array} of the program's parameters."""
    prog = program or default_main_program()
    return dict(getattr(prog, "params", {}))


def load_program_state(model_path, var_list=None):
    """Reference: paddle.static.load_program_state — returns the raw
    {name: array} dict for set_program_state."""
    from .. import ckpt as _ckpt
    return _ckpt.load(model_path + ".pdparams")


def set_program_state(program, state_dict):
    prog = program or default_main_program()
    if not hasattr(prog, "params"):
        prog.params = {}
    prog.params.update(state_dict)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference: paddle.static.normalize_program — prune the program to
    the feed→fetch closure for inference export.  Program here records a
    pure expression graph already (dead nodes are never executed — the
    Executor evaluates fetches by demand), so this returns the program
    with feeds/fetches pinned."""
    program._normalized_io = ([getattr(v, "name", v) for v in feed_vars],
                              list(fetch_vars))
    return program


class WeightNormParamAttr:
    """Reference: paddle.static.WeightNormParamAttr — ParamAttr requesting
    weight normalisation (w = g·v/||v||).  Consumed by nn.utils.weight_norm;
    carried here so ported configs construct."""

    def __init__(self, dim=None, name=None, initializer=None, trainable=True,
                 **kw):
        self.dim, self.name = dim, name
        self.initializer, self.trainable = initializer, trainable


class ExponentialMovingAverage:
    """Reference: paddle.static.ExponentialMovingAverage — shadow
    parameters s = decay·s + (1-decay)·p with optional Adam-style
    debiasing; apply()/restore() swap them in and out.

    Functional form: ``update(params)`` takes the current {name: array}
    pytree (works with Layer.state_dict or TrainStep params)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = float(decay)
        self._shadow = {}
        self._backup = {}
        self._step = 0

    def update(self, params):
        self._step += 1
        d = self.decay
        for k, v in dict(params).items():
            prev = self._shadow.get(k)
            self._shadow[k] = (1 - d) * v if prev is None \
                else d * prev + (1 - d) * v
        return {k: s / (1 - d ** self._step)
                for k, s in self._shadow.items()}

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Context: yields the debiased shadow dict (reference swaps them
        into scope; here you pass them to your eval step)."""
        d = self.decay
        debiased = {k: s / (1 - d ** max(1, self._step))
                    for k, s in self._shadow.items()}
        self._backup = debiased
        try:
            yield debiased
        finally:
            if need_restore:
                self._backup = {}

    def restore(self, executor=None):
        self._backup = {}


__all__ += ["Variable", "cuda_places", "xpu_places", "npu_places",
            "device_guard", "ipu_shard_guard", "save", "load",
            "save_program_state", "load_program_state", "set_program_state",
            "normalize_program", "WeightNormParamAttr",
            "ExponentialMovingAverage"]


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Reference: paddle.static.py_func (alias of static.nn.py_func)."""
    from .nn import py_func as _impl
    return _impl(func, x, out, backward_func, skip_vars_in_backward_input)


__all__ += ["py_func"]
