"""paddle_tpu.static.nn — the static-graph layer builders.

Reference: python/paddle/static/nn/{common,sequence_lod,control_flow}.py.

Two deliberate TPU-first redesigns (SURVEY §7.0/§7.3):

1. **Parameters are created eagerly** at build time (each call = one layer
   instantiation, exactly the reference's semantics) and the computation
   records onto ``static.Var`` via the op-dispatch layer, or runs
   immediately when given arrays.

2. **LoD sequences become (padded, length)**: the reference's
   variable-length LoD tensor is replaced by a dense ``(B, T, ...)``
   tensor plus a ``(B,)`` length vector — XLA needs static shapes, and
   padded-dense is the layout every TPU sequence model uses anyway.  All
   ``sequence_*`` ops below take/return this pair convention.  Ops whose
   *output* shape is data-dependent (``sequence_unpad``/``sequence_
   reshape``/``sequence_expand``) run on host NumPy: they are dataloader-
   domain transforms, same stance as geometric sampling.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

# control-flow ops (lax-backed, shared with jit)
from ..jit.control_flow import case, cond, switch_case, while_loop  # noqa: F401


def _time_mask(length, T, dtype=jnp.float32):
    return (jnp.arange(T)[None, :] < jnp.asarray(length)[:, None]).astype(dtype)


# ---------------------------------------------------------------------------
# parameterised builders (create params eagerly, then run/record)
# ---------------------------------------------------------------------------

def _builder_param(shape, tag, init, attr=None, is_bias=False):
    """One tracked trainable parameter for an inline builder (nce/prelu/
    sequence_conv/row_conv): created on a host Layer so _track_params
    registers it on the active Program (persisted by static.save,
    visible to optimizers) — ADVICE r4: builders must not bake frozen
    seeded constants."""
    from ..nn.layer import Layer
    host = Layer()
    name = "bias" if is_bias else "weight"
    setattr(host, name, host.create_parameter(
        shape, attr=attr, is_bias=is_bias, default_initializer=init))
    _track_params(host, tag)
    return getattr(host, name)


def _track_params(layer, prefix):
    """Register a builder-created layer's parameters on the active
    Program so static.save/save_program_state persist them (the
    reference's builders register Variables in the block the same way)."""
    from . import default_main_program
    prog = default_main_program()
    if not hasattr(prog, "params"):
        prog.params = {}
    base = f"{prefix}_{len(prog.params)}"
    for pname, p in layer.named_parameters():
        prog.params[f"{base}.{pname}"] = p
    return layer


def fc(x, size, num_flatten_dims=1, activation=None, name=None, **kw):
    """Reference semantics: dims [num_flatten_dims:] flatten into the
    matmul's feature axis; output shape is
    x.shape[:num_flatten_dims] + [size]."""
    import math as _math

    from ..nn.layers_common import Linear
    from . import apply
    if isinstance(num_flatten_dims, str):
        raise TypeError(
            "static.nn.fc: activation is keyword-only "
            "(fc(x, size, activation='relu')) — got a string for "
            "num_flatten_dims")
    nfd = int(num_flatten_dims)
    if any(s in (None, -1) for s in x.shape[nfd:]):
        raise ValueError("static.nn.fc needs static dims past "
                         f"num_flatten_dims={nfd}")
    feat = int(_math.prod(int(s) for s in x.shape[nfd:]))
    layer = _track_params(Linear(feat, size), "fc")
    w, b = layer.weight, layer.bias

    def run(v, ww, bb):
        flat = v.reshape((-1, feat))
        # leading dims from the runtime value (batch may be -1 at build)
        return (flat @ ww + bb).reshape(tuple(v.shape[:nfd]) + (size,))

    out = apply(run, x, w, b)
    if activation == "relu":
        out = apply(jax.nn.relu, out)
    elif activation == "tanh":
        out = apply(jnp.tanh, out)
    elif activation == "softmax":
        out = apply(jax.nn.softmax, out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """size = (vocab, dim).  is_sparse maps to the rows-sparse gradient
    channel (sparse/rows.py) the same way the reference's sparse
    embedding update does."""
    from ..nn.layers_common import Embedding
    layer = _track_params(Embedding(size[0], size[1],
                                    padding_idx=padding_idx), "embedding")
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     dtype="float32", **kw):
    """Reference: static.nn.sparse_embedding — the PS-mode large-table
    embedding; here the table is dense on HBM and updates flow through
    RowsGrad (SURVEY §2.5 parameter-server row)."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, **kw):
    from ..nn.layers_tail4 import BatchNorm
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _track_params(
        BatchNorm(int(ch), act=act, momentum=momentum, epsilon=epsilon,
                  param_attr=param_attr, bias_attr=bias_attr,
                  data_layout=data_layout), "batch_norm")
    if is_test:
        layer.eval()
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn import functional as F
    shape = tuple(int(s) for s in input.shape[begin_norm_axis:])
    from ..nn.layers_common import LayerNorm
    layer = _track_params(LayerNorm(shape, epsilon=epsilon), "layer_norm")
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ..nn.layers_common import GroupNorm
    layer = _track_params(GroupNorm(groups, int(input.shape[1]),
                                    epsilon=epsilon), "group_norm")
    out = layer(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn.layers_conv import InstanceNorm2D
    layer = _track_params(InstanceNorm2D(int(input.shape[1]),
                                         epsilon=epsilon), "instance_norm")
    return layer(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, **kw):
    """Reference: static.nn.data_norm — normalisation by accumulated batch
    statistics (batch_size/batch_sum/batch_square_sum), used by CTR
    models.  Statistics initialise to the reference defaults (size 1e4,
    sum 0, square-sum 1e4 → mean 0, var 1)."""
    d = int(input.shape[-1])
    batch_size = jnp.full((d,), 1e4, jnp.float32)
    batch_sum = jnp.zeros((d,), jnp.float32)
    batch_sq = jnp.full((d,), 1e4, jnp.float32)
    mean = batch_sum / batch_size
    var = batch_sq / batch_size - mean ** 2
    out = (input - mean) / jnp.sqrt(var + epsilon)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, act=None, param_attr=None, bias_attr=None,
           data_format="NCHW", name=None):
    from ..nn.layers_common import Conv2D
    layer = _track_params(
        Conv2D(int(input.shape[1]), num_filters, filter_size,
               stride=stride, padding=padding, dilation=dilation,
               groups=groups, data_format=data_format), "conv2d")
    out = layer(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, act=None, **kw):
    from ..nn.layers_conv import Conv3D
    layer = _track_params(
        Conv3D(int(input.shape[1]), num_filters, filter_size,
               stride=stride, padding=padding, dilation=dilation,
               groups=groups), "conv3d")
    out = layer(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1, act=None,
                     **kw):
    from ..nn.layers_conv import Conv2DTranspose
    layer = _track_params(
        Conv2DTranspose(int(input.shape[1]), num_filters, filter_size,
                        stride=stride, padding=padding,
                        dilation=dilation, groups=groups), "conv2d_transpose")
    out = layer(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def conv3d_transpose(input, num_filters, filter_size=None, stride=1,
                     padding=0, dilation=1, groups=1, act=None, **kw):
    from ..nn.layers_tail4 import Conv3DTranspose
    layer = _track_params(
        Conv3DTranspose(int(input.shape[1]), num_filters, filter_size,
                        stride=stride, padding=padding,
                        dilation=dilation, groups=groups), "conv3d_transpose")
    out = layer(input)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  **kw):
    from ..vision.ops import DeformConv2D
    layer = DeformConv2D(int(input.shape[1]), num_filters, filter_size,
                         stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
    return layer(input, offset, mask)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    """mode: all (one alpha) / channel / element.  ``alpha`` is a tracked
    TRAINABLE parameter (reference: the builder creates a Parameter the
    optimizer updates and static.save persists), not a frozen constant."""
    from ..nn import functional as F
    from ..nn import initializer as I
    # channel axis follows data_format (NCHW: axis 1; NHWC/NLC: last)
    ch_ax = 1 if data_format.startswith("NC") else getattr(x, "ndim", 2) - 1
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        shape = (int(x.shape[ch_ax]),)
    else:
        shape = tuple(int(s) for s in x.shape[1:])

    alpha = _builder_param(shape, "prelu", I.Constant(0.25),
                           attr=param_attr)
    if mode == "channel" and getattr(x, "ndim", 2) > 2:
        # per-channel alpha must broadcast along the channel axis, not the
        # trailing one (pre-round-5 this path raised on NCHW inputs)
        bshape = [1] * x.ndim
        bshape[ch_ax] = shape[0]
        alpha = alpha.reshape(bshape)
    return F.prelu(x, alpha)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Power-iteration spectral normalisation of a weight tensor."""
    w = jnp.moveaxis(jnp.asarray(weight), dim, 0)
    mat = w.reshape(w.shape[0], -1)
    u = jnp.ones((mat.shape[0],), mat.dtype) / math.sqrt(mat.shape[0])
    for _ in range(max(1, power_iters)):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    return jnp.moveaxis((w / sigma), 0, dim)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Reference: static.nn.row_conv — lookahead convolution
    out[t] = Σ_{i=0..C} x[t+i] ∘ filter[i] (zero past the end)."""
    x = jnp.asarray(input)                      # (B, T, D)
    C = int(future_context_size)
    D = int(x.shape[-1])
    from ..nn import initializer as I
    filt = _builder_param((C + 1, D), "row_conv",
                          I.Constant(1.0 / (C + 1)), attr=param_attr)
    outs = 0.0
    for i in range(C + 1):
        shifted = jnp.pad(x[:, i:], ((0, 0), (0, i), (0, 0)))
        outs = outs + shifted * filt[i]
    if act:
        from ..nn import functional as F
        outs = getattr(F, act)(outs)
    return outs


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Reference: static.nn.nce — noise-contrastive estimation loss with a
    uniform negative sampler; per-sample loss (B, 1).  The class weights/
    bias are tracked TRAINABLE parameters (the reference builder creates
    Parameters the optimizer updates and static.save persists)."""
    from ..core import random as prandom
    from ..nn import initializer as I
    x = jnp.asarray(input)                       # (B, D)
    lab = jnp.asarray(label).reshape(-1)
    B, D = x.shape
    V, S = int(num_total_classes), int(num_neg_samples)
    w = _builder_param((V, D), "nce", I.Normal(0.0, 1.0 / math.sqrt(D)),
                       attr=param_attr)
    b = _builder_param((V,), "nce", I.Constant(0.0), attr=bias_attr,
                       is_bias=True)
    key = jax.random.PRNGKey(int(seed)) if seed else \
        prandom.next_key("nce")
    neg = jax.random.randint(key, (B, S), 0, V)
    logq = math.log(S / V)  # uniform noise: S·q(y) = S/V
    pos_logit = jnp.sum(x * w[lab], -1) + b[lab] - logq
    neg_logit = jnp.einsum("bd,bsd->bs", x, w[neg]) + b[neg] - logq
    loss = jax.nn.softplus(-pos_logit) + \
        jnp.sum(jax.nn.softplus(neg_logit), -1)
    return loss[:, None]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference: static.nn.py_func — run host Python inside the graph.
    Maps onto ``jax.pure_callback`` (result shape/dtype taken from the
    ``out`` template); ``backward_func`` becomes a custom VJP whose
    cotangent also round-trips through host."""
    xs = tuple(x) if isinstance(x, (list, tuple)) else (x,)
    template = jax.tree.map(
        lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), out)

    def call(*args):
        # concrete args → run on host directly (also sidesteps PJRT
        # plugins without host-callback support); tracers → pure_callback
        if not any(isinstance(a, jax.core.Tracer) for a in args):
            res = func(*[np.asarray(a) for a in args])
            return jax.tree.map(jnp.asarray, res)
        return jax.pure_callback(func, template, *args)

    if backward_func is None:
        return call(*xs)

    @jax.custom_vjp
    def f(*args):
        return call(*args)

    def fwd(*args):
        return call(*args), args

    def bwd(res, g):
        grads = jax.pure_callback(
            backward_func,
            jax.tree.map(lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype),
                         res),
            *res, g)
        return tuple(grads) if isinstance(grads, (list, tuple)) else (grads,)

    f.defvjp(fwd, bwd)
    return f(*xs)


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """Reference: static.nn.static_pylayer — custom forward/backward pair
    as a graph op; identical mechanics to autograd.PyLayer on custom_vjp."""
    if backward_fn is None:
        return forward_fn(*inputs)

    @jax.custom_vjp
    def f(*args):
        return forward_fn(*args)

    def fwd(*args):
        return forward_fn(*args), args

    def bwd(res, g):
        out = backward_fn(g)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    f.defvjp(fwd, bwd)
    return f(*inputs)


# ---------------------------------------------------------------------------
# sequence ops over the (padded, length) convention
# ---------------------------------------------------------------------------

def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Concatenated rows (total, D) + length (B,) → (padded (B, L, D),
    length).  The inverse of sequence_unpad."""
    if length is None:
        raise ValueError("sequence_pad needs the per-sequence length "
                         "vector (the (padded, length) convention — see "
                         "module docstring)")
    ln = np.asarray(length)
    xs = np.asarray(x)
    L = int(maxlen) if maxlen else int(ln.max())
    D = xs.shape[1:]
    out = np.full((len(ln), L) + D, np.asarray(pad_value), xs.dtype)
    off = 0
    for i, n in enumerate(ln):
        out[i, :n] = xs[off:off + n]
        off += n
    return jnp.asarray(out), jnp.asarray(ln)


def sequence_unpad(x, length, name=None):
    """(B, L, D) + length → concatenated (total, D).  Output shape is
    data-dependent → host-side (dataloader domain)."""
    xs = np.asarray(x)
    ln = np.asarray(length)
    return jnp.asarray(np.concatenate([xs[i, :n] for i, n in enumerate(ln)],
                                      axis=0))


def sequence_pool(input, pool_type, length=None, pad_value=0.0):
    """pool_type: sum/average/sqrt/max/last/first over the valid prefix."""
    x = jnp.asarray(input)
    B, T = x.shape[0], x.shape[1]
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    mask = _time_mask(length, T, x.dtype)
    while mask.ndim < x.ndim:
        mask = mask[..., None]
    pool_type = pool_type.lower()
    if pool_type == "sum":
        return jnp.sum(x * mask, axis=1)
    if pool_type == "average":
        return jnp.sum(x * mask, axis=1) / jnp.maximum(
            jnp.asarray(length, x.dtype)[:, None], 1)
    if pool_type == "sqrt":
        return jnp.sum(x * mask, axis=1) / jnp.sqrt(jnp.maximum(
            jnp.asarray(length, x.dtype)[:, None], 1))
    if pool_type == "max":
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, x.dtype)
        return jnp.max(jnp.where(mask > 0, x, neg), axis=1)
    if pool_type == "last":
        idx = jnp.maximum(jnp.asarray(length) - 1, 0)
        return jnp.take_along_axis(
            x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    if pool_type == "first":
        return x[:, 0]
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length)


def sequence_softmax(input, length=None, name=None):
    x = jnp.asarray(input)
    if length is None:
        return jax.nn.softmax(x, axis=1)
    mask = _time_mask(length, x.shape[1], jnp.float32)
    while mask.ndim < x.ndim:
        mask = mask[..., None]
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, x.dtype)
    return jax.nn.softmax(jnp.where(mask > 0, x, neg), axis=1) * mask.astype(x.dtype)


def sequence_reverse(x, length=None, name=None):
    """Reverse the valid prefix of each row, keep padding in place."""
    x = jnp.asarray(x)
    T = x.shape[1]
    if length is None:
        return jnp.flip(x, axis=1)
    ln = jnp.asarray(length)[:, None]
    t = jnp.arange(T)[None, :]
    src = jnp.where(t < ln, ln - 1 - t, t).astype(jnp.int32)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)


def sequence_concat(input, length=None, name=None):
    """Concatenate sequences time-wise: parts [(B, Ti, D)] + lengths
    [(B,)] → (B, ΣTi, D) packed back-to-back per row, plus new lengths."""
    if length is None:
        return jnp.concatenate([jnp.asarray(p) for p in input], axis=1)
    parts = [jnp.asarray(p) for p in input]
    lens = [jnp.asarray(l) for l in length]
    B = parts[0].shape[0]
    Ltot = sum(int(p.shape[1]) for p in parts)
    total = sum(lens)
    out = jnp.zeros((B, Ltot) + parts[0].shape[2:], parts[0].dtype)
    offset = jnp.zeros((B,), jnp.int32)
    for p, ln in zip(parts, lens):
        T = p.shape[1]
        t = jnp.arange(T)[None, :]
        dstpos = offset[:, None] + t                      # (B, T)
        valid = t < ln[:, None]
        dstpos = jnp.where(valid, dstpos, Ltot)           # drop slot
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], dstpos.shape)
        out = out.at[bidx, dstpos].set(p, mode="drop")
        offset = offset + ln.astype(jnp.int32)
    return out, total


def sequence_expand(x, y_length, ref_level=0, name=None):
    """Repeat each row i of x y_length[i] times (host-side: output rows
    are data-dependent)."""
    xs = np.asarray(x)
    reps = np.asarray(y_length)
    return jnp.asarray(np.repeat(xs, reps, axis=0))


def sequence_expand_as(x, y, name=None):
    xs = np.asarray(x)
    return jnp.asarray(np.repeat(xs, len(np.asarray(y)) // len(xs), axis=0))


def sequence_reshape(input, new_dim, name=None):
    """Re-chunk concatenated rows to a new feature width (host-side)."""
    xs = np.asarray(input)
    return jnp.asarray(xs.reshape(-1, new_dim))


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """(B, T) ids → (B, T, win) sliding windows padded with pad_value."""
    x = jnp.asarray(input)
    outs = []
    T = x.shape[1]
    for i in range(int(win_size)):
        shifted = jnp.pad(x[:, i:], ((0, 0), (0, i)),
                          constant_values=pad_value)
        outs.append(shifted)
    return jnp.stack(outs, axis=-1)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, length=None, act=None, param_attr=None):
    """Context-window convolution over time (reference sequence_conv with
    the default symmetric context padding)."""
    x = jnp.asarray(input)                      # (B, T, D)
    B, T, D = x.shape
    half = (int(filter_size) - 1) // 2
    ctx = []
    for i in range(-half, int(filter_size) - half):
        if i < 0:
            shifted = jnp.pad(x[:, :T + i], ((0, 0), (-i, 0), (0, 0)))
        elif i > 0:
            shifted = jnp.pad(x[:, i:], ((0, 0), (0, i), (0, 0)))
        else:
            shifted = x
        ctx.append(shifted)
    stacked = jnp.concatenate(ctx, axis=-1)     # (B, T, fs*D)
    from ..nn import initializer as I
    fan_in = int(stacked.shape[-1])
    w = _builder_param((fan_in, int(num_filters)), "sequence_conv",
                       I.Normal(0.0, 1.0 / math.sqrt(fan_in)),
                       attr=param_attr)
    out = stacked @ w
    if length is not None:
        out = out * _time_mask(length, T, out.dtype)[..., None]
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def sequence_slice(input, offset, length, name=None):
    """Per-row slice: row i keeps [offset[i], offset[i]+length[i]).
    Slice length must be uniform (static shapes); returns (B, max_len, D)
    with rows gathered from their offsets."""
    x = jnp.asarray(input)
    off = jnp.asarray(offset).reshape(-1)
    ln = np.asarray(length).reshape(-1)
    L = int(ln.max())
    t = jnp.arange(L)[None, :]
    src = jnp.clip(off[:, None] + t, 0, x.shape[1] - 1).astype(jnp.int32)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    mask = (t < jnp.asarray(ln)[:, None])
    return out * mask.reshape(mask.shape + (1,) * (x.ndim - 2)).astype(x.dtype)


def sequence_scatter(input, index, updates, length=None, name=None):
    """Scatter-add updates into per-row time positions: index (B, K) time
    slots, updates (B, K, D)."""
    x = jnp.asarray(input)
    idx = jnp.asarray(index).astype(jnp.int32)
    upd = jnp.asarray(updates)
    B = x.shape[0]
    bidx = jnp.broadcast_to(jnp.arange(B)[:, None], idx.shape)
    return x.at[bidx, idx].add(upd)


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
