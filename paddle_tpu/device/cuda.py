"""paddle_tpu.device.cuda — accelerator device API at the reference's
CUDA path (reference: python/paddle/device/cuda/__init__.py).

"cuda" here means THE accelerator: every query maps onto the TPU chip's
PJRT runtime stats (``Device.memory_stats()``), so ported OOM-debugging
code (``max_memory_allocated`` prints and friends) reports real HBM
numbers.  Streams/events re-export the device module's TPU-semantic
implementations (XLA owns scheduling; see device/__init__.py).
"""

from __future__ import annotations

import contextlib
import re

import jax

from . import Event, Stream, current_stream, synchronize  # noqa: F401


def _accel_devices():
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return devs or jax.devices()


def _dev(device=None):
    devs = _accel_devices()
    if device is None:
        return devs[0]
    idx = getattr(device, "idx", device)
    if isinstance(idx, str):
        # reference accepts 'gpu:0' / 'gpu' / 'tpu:1' string forms
        tail = idx.rsplit(":", 1)[-1]
        idx = int(tail) if tail.isdigit() else 0
    return devs[int(idx) % len(devs)]


def device_count() -> int:
    return len(_accel_devices())


def get_device_name(device=None) -> str:
    return getattr(_dev(device), "device_kind", "cpu")


def get_device_capability(device=None):
    """Reference returns (major, minor) CUDA capability; the TPU analogue
    is (generation, core-count-on-chip)."""
    d = _dev(device)
    kind = getattr(d, "device_kind", "")
    m = re.search(r"\d+", kind)  # FIRST number: 'TPU v5 lite' -> 5
    return (int(m.group()) if m else 0, getattr(d, "num_cores", 1) or 1)


class _DeviceProperties:
    def __init__(self, name, total_memory, major, minor,
                 multi_processor_count):
        self.name = name
        self.total_memory = total_memory
        self.major, self.minor = major, minor
        self.multi_processor_count = multi_processor_count

    def __repr__(self):
        return (f"_gpuDeviceProperties(name='{self.name}', "
                f"major={self.major}, minor={self.minor}, "
                f"total_memory={self.total_memory // (1024 ** 2)}MB, "
                f"multi_processor_count={self.multi_processor_count})")


def get_device_properties(device=None):
    d = _dev(device)
    major, minor = get_device_capability(device)
    return _DeviceProperties(getattr(d, "device_kind", "cpu"),
                             _stats(d).get("bytes_limit", 0), major, minor,
                             getattr(d, "num_cores", 1) or 1)


def _stats(d) -> dict:
    try:
        return d.memory_stats() or {}
    except Exception:  # backend without stats (CPU test mesh)
        return {}


def memory_allocated(device=None) -> int:
    """Reference: paddle.device.cuda.memory_allocated — live bytes."""
    return int(_stats(_dev(device)).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    s = _stats(_dev(device))
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    """Reference: allocator-pool bytes; PJRT reports the HBM limit as the
    reservation (the TPU runtime owns all of HBM)."""
    s = _stats(_dev(device))
    return int(s.get("bytes_reserved", s.get("bytes_limit", 0)))


def max_memory_reserved(device=None) -> int:
    s = _stats(_dev(device))
    return int(s.get("peak_bytes_reserved", s.get("bytes_limit", 0)))


def empty_cache():
    """Reference: release cached allocator blocks.  XLA's allocator keeps
    HBM for the process; freeing Python references is what actually
    releases buffers — this triggers a GC pass for parity."""
    import gc
    gc.collect()


def stream_guard(stream):
    """Reference: paddle.device.cuda.stream_guard — XLA schedules its own
    streams, so the guard is a no-op context (kept for ported code)."""
    return contextlib.nullcontext(stream)


__all__ = ["Stream", "Event", "current_stream", "synchronize",
           "device_count", "get_device_name", "get_device_capability",
           "get_device_properties", "memory_allocated",
           "max_memory_allocated", "memory_reserved",
           "max_memory_reserved", "empty_cache", "stream_guard"]
