"""Device API (``paddle.device`` parity).

Reference: python/paddle/device/ — set_device / get_device / Stream /
Event / synchronize.  On TPU, XLA owns streams and events; the Stream/Event
objects here preserve the reference API shape (creation, waiting, recording,
elapsed time) with semantics mapped to jax's async dispatch model: an Event
"records" by capturing a completion fence on all pending work.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import (device_count, get_device, is_compiled_with_cuda,  # noqa: F401
                    is_compiled_with_tpu, local_device_count, set_device,
                    synchronize)

__all__ = ["set_device", "get_device", "device_count", "local_device_count",
           "synchronize", "Stream", "Event", "current_stream",
           "is_compiled_with_cuda", "is_compiled_with_tpu", "XPUPlace",
           "CPUPlace", "TPUPlace", "CUDAPinnedPlace", "get_available_device"]


def get_available_device() -> str:
    return get_device()


class TPUPlace:
    def __init__(self, idx: int = 0):
        self.idx = idx

    def __repr__(self):
        return f"TPUPlace({self.idx})"


class CPUPlace:
    def __repr__(self):
        return "CPUPlace()"


XPUPlace = TPUPlace  # accelerator place alias for ported scripts


class CUDAPinnedPlace:
    """Reference: paddle.CUDAPinnedPlace — page-locked host staging memory.
    On TPU the analogue is host memory XLA stages transfers from; arrays
    placed here live on the host (``memory_kind='pinned_host'`` where the
    runtime supports it, plain host otherwise)."""

    def __repr__(self):
        return "CUDAPinnedPlace()"


class Event:
    """``paddle.device.Event`` parity.  ``record()`` fences all work enqueued
    so far; ``synchronize()`` blocks on that fence; ``elapsed_time`` between
    two synchronized events is host wall-clock in ms."""

    def __init__(self, enable_timing: bool = True):
        self.enable_timing = enable_timing
        self._fence: Optional[jax.Array] = None
        self._time_ns: Optional[int] = None

    def record(self, stream: "Stream" = None):
        del stream
        self._fence = jnp.zeros(()) + 0  # enqueued after all pending work
        if self.enable_timing:
            # host wall-clock at enqueue: elapsed_time between two events
            # measures enqueue-to-enqueue (for device-time-accurate numbers
            # block between records, or use the profiler's device trace)
            self._time_ns = time.perf_counter_ns()

    def query(self) -> bool:
        if self._fence is None:
            return True
        try:
            return self._fence.is_ready()
        except AttributeError:
            return True

    def synchronize(self):
        if self._fence is not None:
            self._fence.block_until_ready()

    def elapsed_time(self, end: "Event") -> float:
        self.synchronize()
        end.synchronize()
        if self._time_ns is None or end._time_ns is None:
            raise RuntimeError("events must be recorded with enable_timing")
        return (end._time_ns - self._time_ns) / 1e6


class Stream:
    """``paddle.device.Stream`` parity.  XLA schedules internally; a Stream
    here is an ordering scope whose synchronize() drains the device."""

    def __init__(self, device=None, priority: int = 2):
        self.device = device
        self.priority = priority

    def record_event(self, event: Optional[Event] = None) -> Event:
        event = event or Event()
        event.record(self)
        return event

    def wait_event(self, event: Event):
        event.synchronize()

    def wait_stream(self, stream: "Stream"):
        stream.synchronize()

    def synchronize(self):
        synchronize()

    def query(self) -> bool:
        return True


_default_stream = Stream()


def current_stream(device=None) -> Stream:
    del device
    return _default_stream


def __getattr__(name):
    if name == "cuda":  # paddle.device.cuda — the accelerator stats API
        import importlib
        mod = importlib.import_module(".cuda", __name__)
        globals()["cuda"] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu.device' has no attribute "
                         f"{name!r}")
