"""hapi callbacks (reference: python/paddle/hapi/callbacks.py).

Callback zoo: ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
plus the config/dispatch machinery (``config_callbacks`` → CallbackList).
VisualDL is replaced by ``LogWriterCallback`` writing JSONL through
paddle_tpu.metrics sinks (VisualDL itself is GPU-stack tooling).
"""

from __future__ import annotations

import numbers
import os
import time
from typing import Dict, List, Optional


def _scalar(v):
    """Materialize a 0-d device array to a Python float (logs carry device
    arrays until a callback actually consumes them)."""
    if hasattr(v, "ndim") and getattr(v, "ndim", None) == 0:
        return float(v)
    return v


class Callback:
    """Base class; hooks mirror the reference exactly so ported callbacks
    drop in."""

    def __init__(self):
        self.model = None
        self.params: Dict = {}

    def set_params(self, params: Dict):
        self.params = dict(params or {})

    def set_model(self, model):
        self.model = model

    # train
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    # eval
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    # predict
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def call(self, name, *args, **kwargs):
        for c in self.callbacks:
            getattr(c, name)(*args, **kwargs)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a, **k: self.call(name, *a, **k)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch progress logging (reference ProgBarLogger; verbose 0/1/2)."""

    def __init__(self, log_freq: int = 10, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            v = _scalar(v)
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple)) and v and isinstance(v[0], numbers.Number):
                parts.append(f"{k}: " + "/".join(f"{x:.4f}" for x in v))
        return " - ".join(parts)

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.monotonic()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and (step + 1) % self.log_freq == 0:
            n = self.params.get("steps")
            print(f"step {step + 1}/{n if n else '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.monotonic() - self._t0
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Save every ``save_freq`` epochs + final (reference ModelCheckpoint)."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference parity:
    monitor/mode/patience/min_delta/baseline/save_best_model)."""

    def __init__(self, monitor: str = "loss", mode: str = "auto",
                 patience: int = 0, verbose: int = 1, min_delta: float = 0.0,
                 baseline: Optional[float] = None,
                 save_best_model: bool = True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = -1

    def _better(self, cur, best) -> bool:
        if best is None:
            return True
        delta = cur - best
        return delta > self.min_delta if self.mode == "max" \
            else -delta > self.min_delta

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: no {self.monitor} improvement "
                          f"in {self.wait} evals; stopping")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference: LRScheduler callback;
    by_step/by_epoch). Our schedules are pure step-count functions inside
    the compiled step, so this only drives *stateful* schedulers (e.g.
    ReduceOnPlateau-style) that expose ``.step()``."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class SpeedMonitor(Callback):
    """Throughput (and optional MFU) per logging window.

    SURVEY §5.5 TPU-equivalent: per-step timing, samples/sec, tokens/sec
    and MFU computed in the trainer loop. ``tokens_per_sample`` turns
    samples/sec into tokens/sec; ``flops_per_sample`` + the device's peak
    enables MFU."""

    def __init__(self, log_freq: int = 10, batch_size: Optional[int] = None,
                 tokens_per_sample: Optional[int] = None,
                 flops_per_sample: Optional[float] = None,
                 peak_flops: Optional[float] = None, verbose: int = 1):
        super().__init__()
        self.log_freq = log_freq
        self.batch_size = batch_size
        self.tokens_per_sample = tokens_per_sample
        self.flops_per_sample = flops_per_sample
        self.peak_flops = peak_flops
        self.verbose = verbose
        self.last: Dict[str, float] = {}

    def on_train_begin(self, logs=None):
        self._reset_window()

    def on_epoch_begin(self, epoch, logs=None):
        # windows must not span epoch boundaries (epoch-begin overhead)
        self._reset_window()

    def on_eval_end(self, logs=None):
        # nor an eval pass run mid-training
        self._reset_window()

    def _reset_window(self):
        self._t0 = time.monotonic()
        self._n = 0

    def on_train_batch_end(self, step, logs=None):
        self._n += 1
        if self._n % self.log_freq:
            return
        dt = time.monotonic() - self._t0
        self._t0 = time.monotonic()
        steps_per_sec = self.log_freq / max(dt, 1e-9)
        stats = {"steps_per_sec": steps_per_sec,
                 "ms_per_step": 1000.0 / steps_per_sec}
        bs = self.batch_size or self.params.get("batch_size")
        if bs:
            sps = steps_per_sec * bs
            stats["samples_per_sec"] = sps
            if self.tokens_per_sample:
                stats["tokens_per_sec"] = sps * self.tokens_per_sample
            if self.flops_per_sample and self.peak_flops:
                stats["mfu"] = sps * self.flops_per_sample / self.peak_flops
        self.last = stats
        if logs is not None:
            logs.update(stats)
        if self.verbose:
            print(" - ".join(f"{k}: {v:.4g}" for k, v in stats.items()))


class LogWriterCallback(Callback):
    """JSONL metric stream (in place of the reference's VisualDL callback)."""

    def __init__(self, log_dir: str, log_freq: int = 1):
        super().__init__()
        self.log_dir = log_dir
        self.log_freq = log_freq
        self._fh = None
        self._global_step = 0

    def on_train_begin(self, logs=None):
        import json  # noqa: F401 — opened lazily so predict-only runs skip IO
        os.makedirs(self.log_dir, exist_ok=True)
        self._fh = open(os.path.join(self.log_dir, "metrics.jsonl"), "a")

    def _write(self, tag, step, logs):
        import json
        if self._fh is None:
            return
        rec = {"tag": tag, "step": int(step)}
        for k, v in (logs or {}).items():
            v = _scalar(v)
            if isinstance(v, numbers.Number):
                rec[k] = float(v)
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        if (step + 1) % self.log_freq == 0:
            self._write("train", self._global_step, logs)

    def on_eval_end(self, logs=None):
        # stamped with the training global step so multi-epoch eval curves
        # are ordered
        self._write("eval", self._global_step, logs)

    def on_train_end(self, logs=None):
        if self._fh:
            self._fh.close()
            self._fh = None


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=10, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train",
                     batch_size=None) -> CallbackList:
    """Assemble the default callback set around user callbacks (reference
    config_callbacks)."""
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or [], "save_dir": save_dir,
                    "mode": mode, "batch_size": batch_size})
    return lst


class VisualDL(LogWriterCallback):
    """Reference call-shape alias (paddle.callbacks.VisualDL): streams the
    same scalars to ``log_dir`` as JSONL — point any dashboard at
    ``metrics.jsonl`` (the VisualDL binary itself is a separate non-pip
    service; the callback contract is what the framework owes)."""


class ReduceLROnPlateau(Callback):
    """Reference: paddle.callbacks.ReduceLROnPlateau — scale the LR by
    ``factor`` after ``patience`` epochs without improvement in the
    monitored metric.

    The plateau state machine is optimizer.lr.ReduceOnPlateau (ONE
    implementation of best/bad-count/cooldown semantics); this callback
    only monitors the metric, drives ``scheduler.step(metric)``, and
    copies the resulting LR onto the Model's optimizer via
    ``get_lr``/``set_lr``.  The scheduler fires when bad epochs EXCEED
    its patience, while the callback contract is "reduce once
    ``patience`` epochs fail to improve" — so the scheduler is built
    with ``patience - 1`` to keep callback semantics.  (Known minor
    divergence: the scheduler ticks cooldown only on non-improving
    epochs.)"""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.verbose = verbose
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        mode = "max" if (mode == "auto" and "acc" in monitor) else \
            ("min" if mode == "auto" else mode)
        self._sched_kw = dict(mode=mode, factor=float(factor),
                              patience=int(patience) - 1,
                              threshold=abs(min_delta),
                              cooldown=int(cooldown), min_lr=float(min_lr))
        self._sched = None

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        opt = getattr(self.model, "_optimizer", None) if self.model else None
        if opt is None or not hasattr(opt, "get_lr"):
            return
        if self._sched is None:
            from ..optimizer.lr import ReduceOnPlateau
            self._sched = ReduceOnPlateau(float(opt.get_lr()),
                                          **self._sched_kw)
        old = float(opt.get_lr())
        self._sched.current = old  # track external LR changes
        self._sched.step(cur)
        new = float(self._sched.current)
        if new < old:
            opt.set_lr(new)
            if self.verbose:
                print(f"ReduceLROnPlateau: epoch {epoch}: "
                      f"lr {old:.2e} -> {new:.2e}")
