"""``paddle.Model`` high-level API (reference: python/paddle/hapi/model.py).

TPU redesign: the reference's Model drives dygraph per-op execution (or a
static Program); here fit/evaluate/predict drive ONE jitted step each —
train step = value_and_grad + optimizer apply with donated state, eval /
predict steps = jitted pure forwards — so the whole epoch loop runs without
per-op Python dispatch. Host-side work is only metric accumulation
(paddle_tpu.metrics NumPy reducers) and callbacks.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..nn.layer import Layer, functional_call, raw_params
from ..observability import _state as _obs_state
from ..observability.spans import span as _span
from ..resilience import _state as _rs_state
from .callbacks import config_callbacks


def _as_tuple(x):
    if x is None:
        return ()
    return tuple(x) if isinstance(x, (tuple, list)) else (x,)


class Model:
    """Wraps a ``Layer`` with fit/evaluate/predict/save/load.

    ``inputs``/``labels`` (InputSpec lists in the reference) are optional
    here — jax shapes flow from the data — but their *lengths* still define
    how a dataloader batch tuple splits into inputs vs labels (default: all
    but the last element are inputs)."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._site = f"hapi.Model({type(network).__name__})"
        self.stop_training = False
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List = []
        self._state: Optional[Dict[str, Any]] = None
        self._train_step = None
        self._forward_step = None

    # -- setup -------------------------------------------------------------

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        del amp_configs  # bf16 is the TPU default; fp16 GradScaler lives in jit.TrainStep
        self._optimizer = optimizer
        self._loss = loss
        ms = _as_tuple(metrics)
        from ..metrics import Metric
        for m in ms:
            if not isinstance(m, Metric):
                raise ValueError(f"metrics must be paddle_tpu.metrics.Metric, got {m!r}")
        self._metrics = list(ms)
        self._train_step = self._forward_step = None
        self._state = None

    def _n_labels(self) -> int:
        return len(self._labels) if self._labels else 1

    def _split_batch(self, batch):
        if isinstance(batch, dict):
            raise ValueError("hapi Model expects tuple/list batches "
                             "(inputs..., labels...)")
        batch = _as_tuple(batch)
        n = self._n_labels()
        if len(batch) <= n:   # predict-style batch: everything is input
            return batch, ()
        return batch[:-n], batch[-n:]

    def _ensure_state(self):
        if self._state is None:
            params = raw_params(self.network)
            self._state = {"params": params, "step": jnp.zeros((), jnp.int32),
                           "rng": jax.random.key(0)}
            if self._optimizer is not None:
                self._state["opt"] = self._optimizer.init(params)
        return self._state

    # -- compiled steps ----------------------------------------------------

    def _build_train_step(self):
        net, opt, loss_fn = self.network, self._optimizer, self._loss

        def compute_loss(params, inputs, labels, key):
            preds = functional_call(net, params, *inputs, rngs=key,
                                    training=True)
            loss = loss_fn(*(_as_tuple(preds) + tuple(labels)))
            return loss, _as_tuple(preds)

        @jax.jit
        def step(state, inputs, labels):
            key = jax.random.fold_in(state["rng"], state["step"])
            (loss, preds), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(state["params"], inputs, labels,
                                            key)
            params, opt_state = opt.apply(grads, state["opt"],
                                          state["params"])
            new = {"params": params, "opt": opt_state,
                   "step": state["step"] + 1, "rng": state["rng"]}
            return new, loss, preds

        return step

    def _infer_step(self):
        """One shared jitted inference forward for eval AND predict (they
        are identical programs; two attributes would compile twice)."""
        if self._forward_step is None:
            net = self.network

            @jax.jit
            def step(params, inputs):
                return _as_tuple(functional_call(net, params, *inputs,
                                                 training=False))

            self._forward_step = step
        return self._forward_step

    # -- batch-level API (reference train_batch/eval_batch/predict_batch) --

    def _train_one(self, inputs, labels):
        """Run one compiled train step; loss stays ON DEVICE (no host sync —
        fit() materializes it only at log boundaries)."""
        if self._optimizer is None or self._loss is None:
            raise RuntimeError("call prepare(optimizer, loss) before training")
        # fault-injection site "step" (hapi drives its own jitted step, so
        # it checks the hook itself, like the telemetry hook below)
        fi = _rs_state.FAULTS[0]
        if fi is not None:
            fi("step")
        if self._train_step is None:
            self._train_step = self._build_train_step()
        state = self._ensure_state()
        inputs, labels = _as_tuple(inputs), _as_tuple(labels)
        # telemetry: one falsy check when disabled (same contract as
        # jit.TrainStep.__call__); hapi drives its own jitted step, so it
        # feeds the StepMonitor directly
        mon = _obs_state.MONITOR[0]
        if mon is not None:
            self._state, loss, preds = mon.timed_step(
                self._site, self.network, inputs,
                lambda: self._train_step(state, inputs, labels))
        else:
            self._state, loss, preds = self._train_step(state, inputs, labels)
        metric_out = self._update_metrics(preds, labels) if self._metrics else {}
        return loss, metric_out

    def train_batch(self, inputs, labels=None):
        loss, metric_out = self._train_one(inputs, labels)
        return float(loss), metric_out

    def eval_batch(self, inputs, labels=None):
        state = self._ensure_state()
        inputs, labels = _as_tuple(inputs), _as_tuple(labels)
        preds = self._infer_step()(state["params"], inputs)
        loss = None
        if self._loss is not None and labels:
            loss = float(self._loss(*(preds + labels)))
        metric_out = self._update_metrics(preds, labels)
        return loss, metric_out

    def predict_batch(self, inputs):
        state = self._ensure_state()
        preds = self._infer_step()(state["params"], _as_tuple(inputs))
        return [jax.device_get(p) for p in preds]

    def _update_metrics(self, preds, labels):
        out = {}
        for m in self._metrics:
            res = m.compute(*(tuple(preds) + tuple(labels)))
            m.update(*_as_tuple(res))
            names, vals = m.name(), m.accumulate()
            # Metric.name()/accumulate() return lists for multi-output
            # metrics (e.g. Accuracy with several topk)
            if isinstance(names, (list, tuple)):
                for n, v in zip(names, _as_tuple(vals)):
                    out[n] = v
            else:
                out[names] = vals
        return out

    # -- loops -------------------------------------------------------------

    def _to_loader(self, data, batch_size, shuffle):
        from ..io import DataLoader, Dataset, IterableDataset
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, (Dataset, IterableDataset)):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            shuffle=True, callbacks=None):
        loader = self._to_loader(train_data, batch_size, shuffle)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir, batch_size=batch_size,
                                metrics=[m.name() for m in self._metrics])
        self.stop_training = False
        cbks.on_train_begin()
        logs: Dict[str, Any] = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            # epoch span: duration histogram + chrome-trace slot sharing
            # the per-step event vocabulary (docs/OBSERVABILITY.md)
            with _span("hapi.fit.epoch", site=self._site, epoch=epoch):
                for step, batch in enumerate(loader):
                    cbks.on_train_batch_begin(step)
                    inputs, labels = self._split_batch(batch)
                    # loss stays a device array here; callbacks
                    # materialize it only when they actually log
                    # (log_freq / epoch end)
                    loss, metric_out = self._train_one(inputs, labels)
                    logs = {"loss": loss, **metric_out}
                    cbks.on_train_batch_end(step, logs)
                    if self.stop_training:
                        break
            logs = {k: (float(v) if hasattr(v, "ndim") else v)
                    for k, v in logs.items()}
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size=batch_size,
                                          verbose=0, _callbacks=cbks)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 callbacks=None, _callbacks=None):
        loader = self._to_loader(eval_data, batch_size, shuffle=False)
        cbks = _callbacks or config_callbacks(
            callbacks, model=self, log_freq=log_freq, verbose=verbose,
            metrics=[m.name() for m in self._metrics], mode="eval")
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []   # (loss, n_samples) — sample-weighted so a short final
        metric_out: Dict[str, Any] = {}   # batch doesn't skew the mean
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            loss, metric_out = self.eval_batch(inputs, labels)
            if loss is not None:
                first = (labels or inputs)[0]
                n = int(first.shape[0]) if hasattr(first, "shape") else 1
                losses.append((loss, n))
            cbks.on_eval_batch_end(step, {"loss": loss, **metric_out})
        logs = dict(metric_out)
        if losses:
            total = sum(n for _, n in losses)
            logs["loss"] = sum(l * n for l, n in losses) / max(total, 1)
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, callbacks=None, verbose=0):
        loader = self._to_loader(test_data, batch_size, shuffle=False)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                mode="predict")
        cbks.on_predict_begin()
        outputs: List = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            inputs, _ = self._split_batch(batch)
            out = self.predict_batch(inputs)
            outputs.append(out)
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        # regroup: list-of-batches → tuple-of-output-streams (reference shape)
        if not outputs:
            return []
        n_out = len(outputs[0])
        return [[b[i] for b in outputs] for i in range(n_out)]

    # -- persistence -------------------------------------------------------

    def save(self, path: str, training: bool = True):
        """``path + '.pdparams'`` (+ ``'.pdopt'``) like the reference."""
        from .. import save as pt_save
        self._sync_params_to_network()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        pt_save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None and self._state is not None \
                and "opt" in self._state:
            pt_save({"opt": self._state["opt"],
                     "step": self._state["step"]}, path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer: bool = False):
        from .. import load as pt_load
        sd = pt_load(path + ".pdparams")
        if skip_mismatch:
            current = self.network.state_dict()
            dropped = [k for k, v in sd.items()
                       if k in current and hasattr(v, "shape")
                       and tuple(current[k].shape) != tuple(v.shape)]
            for k in dropped:
                sd.pop(k)
            if dropped:
                print(f"Model.load: skipped {len(dropped)} mismatched "
                      f"entries: {dropped}")
        self.network.set_state_dict(sd)
        self._state = None  # re-seeded from network params on next step
        if not reset_optimizer and os.path.exists(path + ".pdopt") \
                and self._optimizer is not None:
            opt = pt_load(path + ".pdopt")
            self._ensure_state()
            self._state["opt"] = opt["opt"]
            self._state["step"] = jnp.asarray(opt["step"])

    def _sync_params_to_network(self):
        """Write the trained functional state back into the Layer."""
        if self._state is not None:
            for k, v in self._state["params"].items():
                self.network._assign_by_path(k, v)

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        lines = [f"{type(self.network).__name__}:"]
        total = 0
        for name, p in self.network.named_parameters():
            n = int(p.size)
            total += n
            lines.append(f"  {name:50s} {str(tuple(p.shape)):20s} {n}")
        lines.append(f"Total params: {total}")
        s = "\n".join(lines)
        print(s)
        return {"total_params": total}


def flops(net, input_size=None, inputs=None, dtype="float32",
          print_detail=False):
    """``paddle.flops`` parity, computed by XLA itself.

    Reference: python/paddle/hapi/dynamic_flops.py walks layers with
    per-type handlers (approximate). TPU-native version: lower the traced
    forward through XLA and read the compiled program's cost analysis —
    exact for whatever the model actually executes, fusions included.
    """
    import jax
    import jax.numpy as jnp

    from ..nn.layer import functional_call, raw_params

    if inputs is None:
        if input_size is None:
            raise ValueError("pass input_size=(...) or inputs=[...]")
        inputs = [jnp.zeros(tuple(input_size), dtype)]
    elif not isinstance(inputs, (list, tuple)):
        inputs = [inputs]

    params = raw_params(net)

    def fwd(p, *xs):
        return functional_call(net, p, *xs, training=False)

    compiled = jax.jit(fwd).lower(params, *inputs).compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns [dict]
        costs = costs[0]
    total = int(costs.get("flops", 0))
    if print_detail:
        n_params = sum(int(v.size) for v in params.values())
        print(f"FLOPs: {total:,}  Params: {n_params:,}")
    return total


def summary(net, input_size=None, dtypes=None):
    """``paddle.summary`` parity: layer-name/shape/param table for a bare
    Layer (reference: python/paddle/hapi/model_summary.py)."""
    lines = [f"{type(net).__name__}:"]
    total = 0
    for name, p in net.named_parameters():
        n = int(p.size)
        total += n
        lines.append(f"  {name:50s} {str(tuple(p.shape)):20s} {n}")
    lines.append(f"Total params: {total}")
    print("\n".join(lines))
    return {"total_params": total}
