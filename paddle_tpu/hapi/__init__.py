"""High-level training API (``paddle.Model`` / ``paddle.hapi`` parity).

Reference: python/paddle/hapi/model.py, callbacks.py, progressbar.py.
"""

from .callbacks import (Callback, CallbackList, EarlyStopping,  # noqa: F401
                        LogWriterCallback, LRScheduler, ModelCheckpoint,
                        ProgBarLogger, SpeedMonitor, config_callbacks)
from .model import Model, flops, summary  # noqa: F401
