"""``paddle.geometric`` parity: graph message passing + segment ops.

Reference: python/paddle/geometric/ (message_passing/send_recv.py —
``send_u_recv``, ``send_ue_recv``, ``segment_sum/mean/max/min``; sampling
lives in PGL and is out of the core surface).

TPU redesign: everything lowers to ``jax.ops.segment_*`` scatter-reduces,
which XLA turns into efficient sorted-segment kernels; fixed
``num_segments`` keeps shapes static for jit (pass ``out_size`` — the
reference's knob — whenever the node count is known; defaults fall back
to ``int(dst.max()) + 1`` which forces a host sync outside jit).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv"]


def _num_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    return int(jnp.max(ids)) + 1


def segment_sum(data, segment_ids, out_size: Optional[int] = None):
    return jax.ops.segment_sum(data, segment_ids,
                               num_segments=_num_segments(segment_ids,
                                                          out_size))


def segment_mean(data, segment_ids, out_size: Optional[int] = None):
    n = _num_segments(segment_ids, out_size)
    tot = jax.ops.segment_sum(data, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones(data.shape[:1], data.dtype),
                              segment_ids, num_segments=n)
    cnt = cnt.reshape((n,) + (1,) * (data.ndim - 1))
    return tot / jnp.maximum(cnt, 1)


def _empty_segment_mask(data, segment_ids, n):
    """[n,1,...] bool mask of segments with zero members — detected by
    count, not by sentinel value, so integer dtypes and legitimate ±inf
    maxima are handled correctly."""
    cnt = jax.ops.segment_sum(jnp.ones(segment_ids.shape[:1], jnp.int32),
                              segment_ids, num_segments=n)
    return (cnt == 0).reshape((n,) + (1,) * (data.ndim - 1))


def segment_max(data, segment_ids, out_size: Optional[int] = None):
    n = _num_segments(segment_ids, out_size)
    out = jax.ops.segment_max(data, segment_ids, num_segments=n)
    # reference semantics: empty segments are zero, not the -inf/INT_MIN
    # identity
    empty = _empty_segment_mask(data, segment_ids, n)
    return jnp.where(empty, jnp.zeros((), data.dtype),
                     out).astype(data.dtype)


def segment_min(data, segment_ids, out_size: Optional[int] = None):
    n = _num_segments(segment_ids, out_size)
    out = jax.ops.segment_min(data, segment_ids, num_segments=n)
    empty = _empty_segment_mask(data, segment_ids, n)
    return jnp.where(empty, jnp.zeros((), data.dtype),
                     out).astype(data.dtype)


_REDUCERS = {"sum": segment_sum, "mean": segment_mean,
             "max": segment_max, "min": segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None):
    """Gather source-node features along edges, reduce at destinations
    (reference: paddle.geometric.send_u_recv)."""
    if reduce_op not in _REDUCERS:
        raise ValueError(f"reduce_op must be one of {sorted(_REDUCERS)}")
    return _REDUCERS[reduce_op](x[src_index], dst_index, out_size)


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None):
    """Combine source features with edge features, then reduce at
    destinations (reference: paddle.geometric.send_ue_recv)."""
    msg = x[src_index]
    if message_op == "add":
        msg = msg + y
    elif message_op == "sub":
        msg = msg - y
    elif message_op == "mul":
        msg = msg * y
    elif message_op == "div":
        msg = msg / y
    else:
        raise ValueError("message_op must be add/sub/mul/div")
    if reduce_op not in _REDUCERS:
        raise ValueError(f"reduce_op must be one of {sorted(_REDUCERS)}")
    return _REDUCERS[reduce_op](msg, dst_index, out_size)


# round-4: reindex + neighbor sampling + per-edge messages (host-side
# sampling by design — see sampling.py docstring)
from .sampling import (  # noqa: E402,F401
    reindex_graph, reindex_heter_graph, sample_neighbors, send_uv,
    weighted_sample_neighbors)

__all__ += ["reindex_graph", "reindex_heter_graph", "sample_neighbors",
            "send_uv", "weighted_sample_neighbors"]
