"""Graph reindex + neighbor sampling (reference:
python/paddle/geometric/{reindex,sampling}/*.py).

Design note (SURVEY §7.0 stance): neighbor sampling is dataloader-side
preprocessing — the reference runs it in CPU kernels feeding the trainer,
never on the accelerator.  Here it runs on host NumPy for the same reason
(dynamic output shapes are hostile to XLA and belong off-chip); the
*reindexed* fixed-shape tensors it produces are what go to the TPU.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp


def _reindex(x, neighbors, counts_concat=None):
    """Shared core: local ids with x first, then new neighbor ids in
    order of first appearance.  dst (repeat of target local ids by count)
    is only built when counts aligned with x are given."""
    x = np.asarray(x)
    neighbors = np.asarray(neighbors)
    order = {int(n): i for i, n in enumerate(x)}
    src = np.empty(len(neighbors), np.int64)
    for i, n in enumerate(neighbors):
        n = int(n)
        if n not in order:
            order[n] = len(order)
        src[i] = order[n]
    dst = None
    if counts_concat is not None:
        dst = jnp.asarray(
            np.repeat(np.arange(len(x), dtype=np.int64), counts_concat))
    out_nodes = np.fromiter(order.keys(), np.int64, len(order))
    return (jnp.asarray(src), dst, jnp.asarray(out_nodes))


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None):
    """Reference: paddle.geometric.reindex_graph — map global node ids of
    a sampled subgraph to contiguous local ids (targets first)."""
    return _reindex(x, neighbors, np.asarray(count))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None):
    """Reference: paddle.geometric.reindex_heter_graph — like
    reindex_graph but over per-edge-type neighbor lists sharing one node
    numbering."""
    neigh_all = np.concatenate([np.asarray(n) for n in neighbors])
    src, _, out_nodes = _reindex(x, neigh_all)
    # split src back per edge type; dst is per-type repeat of targets
    sizes = [len(np.asarray(n)) for n in neighbors]
    offs = np.cumsum([0] + sizes)
    srcs = [src[offs[i]:offs[i + 1]] for i in range(len(sizes))]
    dsts = [jnp.asarray(np.repeat(np.arange(len(np.asarray(x)),
                                            dtype=np.int64), np.asarray(c)))
            for c in count]
    return srcs, dsts, out_nodes


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None,
                     rng: Optional[np.random.Generator] = None):
    """Reference: paddle.geometric.sample_neighbors — uniform sampling
    (without replacement) from a CSC graph; returns (out_neighbors,
    out_count[, out_eids])."""
    row = np.asarray(row)
    colptr = np.asarray(colptr)
    nodes = np.asarray(input_nodes)
    rng = rng or np.random.default_rng()
    outs, cnts, out_eids = [], [], []
    for v in nodes:
        lo, hi = int(colptr[v]), int(colptr[v + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(lo, hi)
        else:
            pick = lo + rng.choice(deg, size=sample_size, replace=False)
        outs.append(row[pick])
        cnts.append(len(pick))
        if return_eids:
            out_eids.append(np.asarray(eids)[pick])
    neigh = jnp.asarray(np.concatenate(outs) if outs else
                        np.zeros((0,), row.dtype))
    count = jnp.asarray(np.asarray(cnts, np.int64))
    if return_eids:
        return neigh, count, jnp.asarray(np.concatenate(out_eids))
    return neigh, count


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              rng: Optional[np.random.Generator] = None):
    """Reference: paddle.geometric.weighted_sample_neighbors —
    weight-proportional sampling without replacement (Efraimidis-Spirakis
    exponential-key trick, the reference's GPU kernel algorithm)."""
    row = np.asarray(row)
    colptr = np.asarray(colptr)
    w = np.asarray(edge_weight, np.float64)
    nodes = np.asarray(input_nodes)
    rng = rng or np.random.default_rng()
    outs, cnts, out_eids = [], [], []
    for v in nodes:
        lo, hi = int(colptr[v]), int(colptr[v + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(lo, hi)
        else:
            keys = rng.exponential(size=deg) / np.maximum(w[lo:hi], 1e-30)
            pick = lo + np.argsort(keys)[:sample_size]
        outs.append(row[pick])
        cnts.append(len(pick))
        if return_eids:
            out_eids.append(np.asarray(eids)[pick])
    neigh = jnp.asarray(np.concatenate(outs) if outs else
                        np.zeros((0,), row.dtype))
    count = jnp.asarray(np.asarray(cnts, np.int64))
    if return_eids:
        return neigh, count, jnp.asarray(np.concatenate(out_eids))
    return neigh, count


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Reference: paddle.geometric.send_uv — per-edge message combining
    source and destination node features (no reduce)."""
    xs = jnp.asarray(x)[jnp.asarray(src_index)]
    yd = jnp.asarray(y)[jnp.asarray(dst_index)]
    if message_op == "add":
        return xs + yd
    if message_op == "sub":
        return xs - yd
    if message_op == "mul":
        return xs * yd
    if message_op == "div":
        return xs / yd
    raise ValueError("message_op must be add/sub/mul/div")


def khop_sampler(row, colptr, input_nodes, sample_sizes: Sequence[int],
                 rng: Optional[np.random.Generator] = None):
    """Reference: paddle.incubate.graph_khop_sampler — multi-hop neighbor
    sampling + reindex.  Returns (edge_src, edge_dst, sample_index,
    reindex_x): local-id edges over the union of sampled nodes, the
    global ids of that union (frontier order), and the local ids of the
    original input nodes."""
    rng = rng or np.random.default_rng()
    frontier = np.asarray(input_nodes)
    all_src, all_cnt = [], []
    targets = []
    for size in sample_sizes:
        neigh, cnt = sample_neighbors(row, colptr, frontier,
                                      sample_size=size, rng=rng)
        all_src.append(np.asarray(neigh))
        all_cnt.append(np.asarray(cnt))
        targets.append(frontier)
        # next frontier: newly discovered nodes
        frontier = np.unique(np.asarray(neigh))
    tgt_concat = np.concatenate(targets)
    cnt_concat = np.concatenate(all_cnt)
    neigh_concat = np.concatenate(all_src)
    # one shared numbering: all hop targets first, then new neighbors
    uniq_targets, first_idx = np.unique(tgt_concat, return_index=True)
    ordered_targets = tgt_concat[np.sort(first_idx)]
    src, _, out_nodes = _reindex(ordered_targets, neigh_concat)
    # dst must repeat each *target occurrence* by its count, in local ids
    local = {int(n): i for i, n in enumerate(np.asarray(out_nodes))}
    dst = np.repeat(np.asarray([local[int(t)] for t in tgt_concat],
                               dtype=np.int64), cnt_concat)
    sample_index = out_nodes
    reindex_x = jnp.asarray(np.asarray(
        [local[int(t)] for t in np.asarray(input_nodes)], dtype=np.int64))
    return src, jnp.asarray(dst), sample_index, reindex_x
