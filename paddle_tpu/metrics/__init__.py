"""Metrics (``paddle.metric`` parity).

Reference: python/paddle/metric/metrics.py — Metric base with
``reset/update/accumulate/name``, plus Accuracy / Precision / Recall / Auc.
Metric state lives on host (numpy): metrics consume the (small) per-step
outputs after the compiled step returns, never inside the jit region.
"""

from __future__ import annotations

from typing import List, Sequence, Union

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _np(x) -> np.ndarray:
    return np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        """Optional pre-processing hook run on step outputs; default
        passthrough (reference lets Model.fit call compute then update)."""
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy.  ``update`` accepts either correctness values from
    ``compute`` or raw (pred, label) pairs."""

    def __init__(self, topk: Union[int, Sequence[int]] = (1,), name: str = "acc"):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        if label.ndim == pred.ndim and label.shape[-1] != 1:
            label = label.argmax(-1)  # one-hot -> index
        label = label.reshape(label.shape[: pred.ndim - 1] + (1,)) \
            if label.ndim < pred.ndim else label
        top = np.argsort(-pred, axis=-1)[..., : self.maxk]
        return (top == label).astype(np.float32)

    def update(self, correct, *args):
        correct = _np(correct)
        num = int(np.prod(correct.shape[:-1]))
        for i, k in enumerate(self.topk):
            self.total[i] += float(correct[..., :k].sum())
            self.count[i] += num
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        accs = self.total / np.maximum(self.count, 1)
        return float(accs[0]) if len(self.topk) == 1 else list(map(float, accs))

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision: TP / (TP + FP).  pred is P(class=1)."""

    def __init__(self, name: str = "precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5)
        l = _np(labels).reshape(-1).astype(bool)
        self.tp += int((p & l).sum())
        self.fp += int((p & ~l).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall: TP / (TP + FN)."""

    def __init__(self, name: str = "recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5)
        l = _np(labels).reshape(-1).astype(bool)
        self.tp += int((p & l).sum())
        self.fn += int((~p & l).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via the reference's thresholded histogram estimator
    (num_thresholds buckets over P(class=1))."""

    def __init__(self, curve: str = "ROC", num_thresholds: int = 4095,
                 name: str = "auc"):
        if curve != "ROC":
            raise NotImplementedError("only ROC supported, like the reference")
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _np(labels).reshape(-1)
        buckets = np.minimum((preds * self.num_thresholds).astype(np.int64),
                             self.num_thresholds)
        np.add.at(self._stat_pos, buckets[labels >= 1], 1)
        np.add.at(self._stat_neg, buckets[labels < 1], 1)

    def accumulate(self):
        # trapezoid over descending-threshold cumulative TP/FP
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = np.concatenate([[0.0], tp / tot_pos])
        fpr = np.concatenate([[0.0], fp / tot_neg])
        trapezoid = getattr(np, "trapezoid", np.trapz)
        return float(trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Reference: paddle.metric.accuracy — top-k accuracy of softmax
    ``input`` [N, C] against int ``label`` [N] or [N, 1]."""
    import jax.numpy as jnp
    input = jnp.asarray(input)
    label = jnp.asarray(label).reshape(-1)
    topk = jnp.argsort(-input, axis=-1)[:, :k]
    hit = (topk == label[:, None]).any(axis=-1)
    return hit.mean(dtype=jnp.float32)
