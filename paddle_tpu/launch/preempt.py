"""Preemption-aware training support (SURVEY §5.3).

Reference behavior: elastic restarts rely on user checkpoints; the launcher
sends SIGTERM with a grace window before SIGKILL (launch/job.py). This
module is the trainer-side half: catch the SIGTERM, finish the current
step, save a checkpoint, exit cleanly — so the relaunched job (same or
smaller slice) resumes via ckpt reshard-on-load.

Usage::

    guard = PreemptionGuard(save_fn=lambda: pt.save(state, path))
    with guard:
        for batch in loader:
            state, metrics = step(state, batch)
            if guard.preempted:       # SIGTERM arrived mid-epoch
                break                  # guard saves on exit
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Optional

from ..observability import _state as _obs_state


class PreemptionGuard:
    """Installs a SIGTERM (and optionally SIGINT) handler that flips
    ``preempted`` instead of killing the process; on context exit after a
    preemption, runs ``save_fn`` exactly once."""

    def __init__(self, save_fn: Optional[Callable[[], None]] = None,
                 catch_sigint: bool = False):
        self.save_fn = save_fn
        self._signals = [signal.SIGTERM] + (
            [signal.SIGINT] if catch_sigint else [])
        self._event = threading.Event()
        self._prev = {}
        self._saved = False

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def _handler(self, signum, frame):
        first = not self._event.is_set()
        self._event.set()
        # structured telemetry (timestamp is stamped by the sink layer):
        # interrupted runs are diagnosable from the JSONL stream.  First
        # signal only — the repeat SIGTERM before SIGKILL is not a new
        # preemption.  Guarded hard: a telemetry failure inside a signal
        # handler must never turn a graceful preemption into a crash.
        if not first:
            return
        try:
            reason = signal.Signals(signum).name
        except Exception:
            reason = str(signum)
        if _obs_state.EMIT[0] is not None:
            try:
                mon = _obs_state.MONITOR[0]
                _obs_state.EMIT[0]({
                    "event": "preemption", "reason": reason,
                    "step": mon.total_steps if mon is not None else None})
            except Exception:
                pass
        # drain the flight-recorder ring to the .postmortem file NOW: the
        # grace window may not be honored (SIGKILL follows), and a killed
        # run must never be blind.  write_postmortem never raises, but the
        # hook read is guarded anyway — this is a signal frame.
        pm = _obs_state.POSTMORTEM[0]
        if pm is not None:
            try:
                pm(reason=f"preemption:{reason}")
            except Exception:
                pass

    def __enter__(self):
        # fresh lifecycle per entry: a guard object may be reused across
        # retry attempts, and a stale preempted/saved flag from the last
        # run must not short-circuit the next one
        self._event.clear()
        self._saved = False
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handler)
        return self

    def __exit__(self, exc_type, exc, tb):
        # Save BEFORE restoring handlers: a second SIGTERM during the
        # checkpoint write must not kill the process mid-save.  Restore
        # in a finally: a raising save_fn must not leave the SIGTERM
        # handler installed forever on a dead guard.
        try:
            if self.preempted and self.save_fn is not None \
                    and not self._saved:
                self._saved = True
                self.save_fn()
        finally:
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            self._prev.clear()
        return False

    def checkpoint_now(self):
        """Run save_fn immediately (periodic saves can share the fn).

        Deliberately does NOT mark the exit-time save as done: a later
        preemption must still snapshot the newest state on exit."""
        if self.save_fn is not None:
            self.save_fn()
