"""Job / Pod / Container process model.

Reference: python/paddle/distributed/launch/job/pod.py, job/container.py —
a Pod is the per-node set of Containers; a Container is one training
subprocess with injected env + redirected logs.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


@dataclasses.dataclass
class Container:
    """One training subprocess with env injection and log redirection."""

    entrypoint: List[str]
    env: Dict[str, str]
    log_path: str
    proc: Optional[subprocess.Popen] = None
    _log_file = None

    def start(self) -> None:
        os.makedirs(os.path.dirname(self.log_path) or ".", exist_ok=True)
        self._log_file = open(self.log_path, "ab", buffering=0)
        full_env = {**os.environ, **self.env}
        self.proc = subprocess.Popen(
            self.entrypoint, env=full_env, stdout=self._log_file,
            stderr=subprocess.STDOUT, start_new_session=True)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def returncode(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()

    def terminate(self, grace: float = 10.0) -> None:
        """SIGTERM (checkpoint window for preemption-aware loops), then
        SIGKILL the whole process group."""
        if self.proc is None or self.proc.poll() is not None:
            self._close_log()
            return
        try:
            os.killpg(self.proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and self.proc.poll() is None:
            time.sleep(0.05)
        if self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            self.proc.wait()
        self._close_log()

    def _close_log(self):
        if self._log_file is not None:
            self._log_file.close()
            self._log_file = None


@dataclasses.dataclass
class Pod:
    """Per-node set of containers (reference: job/pod.py)."""

    containers: List[Container] = dataclasses.field(default_factory=list)

    def deploy(self) -> None:
        for c in self.containers:
            c.start()

    def alive(self) -> bool:
        return any(c.alive() for c in self.containers)

    def failed(self) -> bool:
        return any(c.returncode not in (None, 0) for c in self.containers)

    def done(self) -> bool:
        return all(c.returncode == 0 for c in self.containers)

    def stop(self, grace: float = 10.0) -> None:
        for c in self.containers:
            c.terminate(grace)

    def join(self, poll: float = 0.2) -> int:
        """Wait until all containers exit; first nonzero code, else 0."""
        while self.alive():
            time.sleep(poll)
        codes = [c.returncode or 0 for c in self.containers]
        return next((c for c in codes if c), 0)


@dataclasses.dataclass
class Job:
    job_id: str
    nnodes: int
    nproc_per_node: int

    @property
    def world_size(self) -> int:
        return self.nnodes * self.nproc_per_node


def build_container(ctx, global_rank: int, local_rank: int, world_size: int,
                    coordinator: str, endpoints: List[str]) -> Container:
    """Inject the env protocol (reference PADDLE_* names kept for script
    portability; PDTPU_* consumed by paddle_tpu.distributed)."""
    env = {
        # reference protocol (scripts ported from paddle read these)
        "PADDLE_TRAINER_ID": str(global_rank),
        "PADDLE_TRAINERS_NUM": str(world_size),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_CURRENT_ENDPOINT": endpoints[global_rank],
        "PADDLE_MASTER": coordinator,
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_JOB_ID": ctx.job_id,
        # native protocol (paddle_tpu.distributed.init_parallel_env)
        "PDTPU_COORDINATOR": coordinator,
        "PDTPU_PROCESS_ID": str(global_rank),
        "PDTPU_NUM_PROCESSES": str(world_size),
        "PDTPU_LOCAL_RANK": str(local_rank),
    }
    if ctx.devices is not None:
        env["CUDA_VISIBLE_DEVICES"] = ctx.devices
    log_path = os.path.join(ctx.log_dir,
                            f"workerlog.{global_rank}")
    entry = [sys.executable, "-u", ctx.script, *ctx.script_args]
    return Container(entrypoint=entry, env=env, log_path=log_path)
