"""TCPStore: key-value rendezvous for multi-host bootstrap.

Reference: paddle/fluid/distributed/store/tcp_store.cc (TCPStore, Store) —
the blocking KV store every ProcessGroup bootstraps through.

TPU redesign: same wire idea (tiny length-prefixed TCP protocol with
set/get/wait/add/delete/compare_set), implemented over a threaded
socketserver on the master host. jax's own coordination service still
bootstraps the device runtime; this store carries the *launcher-level*
protocol — rank assignment, peer discovery, elastic heartbeats — the part
the reference does with HTTPMaster/ETCDMaster + TCPStore.

A C++ implementation of the same protocol lives in
``paddle_tpu/native/pdtpu_native.cpp`` (built as ``build/libpdtpu_native.so``
via ``make -C native``); ``TCPStore`` uses its server through
ctypes (paddle_tpu.runtime_native) when built, falling back to the pure
Python socketserver here.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional

from ..resilience import _state as _rs_state

_OPS = {"set": 0, "get": 1, "add": 2, "wait": 3, "delete": 4, "cas": 5,
        "list": 6}


def _pack(*fields: bytes) -> bytes:
    out = [struct.pack("<I", len(fields))]
    for f in fields:
        out.append(struct.pack("<I", len(f)))
        out.append(f)
    return b"".join(out)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store peer closed")
        buf += chunk
    return buf


def _unpack(sock: socket.socket):
    (nf,) = struct.unpack("<I", _recv_exact(sock, 4))
    fields = []
    for _ in range(nf):
        (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
        fields.append(_recv_exact(sock, ln))
    return fields


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: "_StoreServer" = self.server  # type: ignore[assignment]
        try:
            while True:
                fields = _unpack(self.request)
                op = fields[0].decode()
                resp = srv.dispatch(op, fields[1:])
                self.request.sendall(_pack(*resp))
        except (ConnectionError, OSError):
            return


class _StoreServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr):
        super().__init__(addr, _Handler)
        self._kv: Dict[str, bytes] = {}
        self._cv = threading.Condition()

    def dispatch(self, op: str, args):
        with self._cv:
            if op == "set":
                self._kv[args[0].decode()] = args[1]
                self._cv.notify_all()
                return [b"ok"]
            if op == "get":
                v = self._kv.get(args[0].decode())
                return [b"ok", v] if v is not None else [b"miss"]
            if op == "add":
                k = args[0].decode()
                cur = int(self._kv.get(k, b"0")) + int(args[1])
                self._kv[k] = str(cur).encode()
                self._cv.notify_all()
                return [b"ok", str(cur).encode()]
            if op == "delete":
                existed = self._kv.pop(args[0].decode(), None) is not None
                self._cv.notify_all()
                return [b"ok" if existed else b"miss"]
            if op == "cas":
                k = args[0].decode()
                if self._kv.get(k) == args[1] or (args[1] == b"" and k not in self._kv):
                    self._kv[k] = args[2]
                    self._cv.notify_all()
                    return [b"ok", args[2]]
                return [b"miss", self._kv.get(k, b"")]
            if op == "list":
                prefix = args[0].decode()
                ks = [k for k in self._kv if k.startswith(prefix)]
                return [b"ok"] + [k.encode() for k in sorted(ks)]
            if op == "wait":
                k = args[0].decode()
                deadline = time.monotonic() + float(args[1])
                while k not in self._kv:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return [b"timeout"]
                    self._cv.wait(remaining)
                return [b"ok", self._kv[k]]
        raise ValueError(f"bad store op {op!r}")


class TCPStore:
    """Client (and, on the master, embedded server) for the rendezvous store.

    ``TCPStore(addr, is_master=True)`` starts the server thread; every
    process (master included) talks to it through a client socket, like the
    reference where rank 0 hosts the store in-process.

    ``retry`` (a ``resilience.RetryPolicy``) makes ``set``/``get`` —
    and the control-plane ops ``add``/``delete``/``compare_set``/
    ``keys`` — survive transient socket failures: a failed op
    reconnects the client socket and re-attempts under the policy (a
    blip in the master's network must cost a heartbeat, not the job;
    a bounced controller must cost a serving worker one retry, not its
    lease mid-epoch).  ``store.set`` / ``store.get`` are registered
    fault-injection sites; the mutating control ops fire ``store.set``
    and ``keys`` fires ``store.get``.  ``compare_set`` is made
    reconnect-idempotent: a retried CAS whose FIRST attempt applied
    server-side (the reply died with the socket) reports success when
    the key now holds the desired value, so a lease-renew chain never
    breaks on its own ghost write.  ``wait`` is deliberately NOT
    retried — its timeout is an answer, not a transient.

    ``set``/``get`` also take a per-call ``timeout=`` override on the
    client socket: one store serves both sub-second heartbeats and
    multi-megabyte KV-page transfer chunks (``serving/disagg.py``), and
    the big payloads need a longer deadline than the liveness probes
    without reconfiguring (or duplicating) the store client.
    """

    def __init__(self, endpoint: str, is_master: bool = False,
                 timeout: float = 60.0, native: Optional[bool] = None,
                 retry=None):
        self.retry = retry
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self.timeout = timeout
        self._server = None
        self._native_server = None
        if is_master:
            use_native = native
            if use_native is None:
                from .. import runtime_native
                use_native = runtime_native.available()
            if use_native:
                # C++ server (paddle_tpu/native/pdtpu_native.cpp) — same wire protocol,
                # immune to GIL stalls in the hosting training process
                from ..runtime_native import StoreServer as _Native
                self._native_server = _Native(host, int(port))
                port = str(self._native_server.port)
            else:
                self._server = _StoreServer((host, int(port)))
                port = str(self._server.server_address[1])
                t = threading.Thread(target=self._server.serve_forever,
                                     daemon=True, name="pdtpu-store")
                t.start()
            self.endpoint = f"{host}:{port}"
        self._sock = self._connect(host, int(port))
        self._lock = threading.Lock()

    def _connect(self, host: str, port: int) -> socket.socket:
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                return socket.create_connection((host, port), timeout=self.timeout)
            except OSError:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"cannot reach store at {host}:{port}")
                time.sleep(0.1)

    def _call(self, op: str, *args: bytes, sock_timeout: Optional[float] = None):
        with self._lock:
            if sock_timeout is not None:
                self._sock.settimeout(sock_timeout)
            try:
                self._sock.sendall(_pack(op.encode(), *args))
                return _unpack(self._sock)
            finally:
                if sock_timeout is not None:
                    self._sock.settimeout(self.timeout)

    def _reconnect(self) -> None:
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            host, port = self.endpoint.rsplit(":", 1)
            self._sock = self._connect(host, int(port))

    def _resilient(self, site: str, fn):
        """Fault-injection check + (optional) retry-with-reconnect around
        one store op.  One falsy check when no injector is installed and
        no policy is configured."""
        def attempt():
            fi = _rs_state.FAULTS[0]
            if fi is not None:
                fi(site)
            try:
                return fn()
            except (ConnectionError, OSError, TimeoutError):
                # the request/response stream is desynchronized (or the
                # socket is dead) — a retry on the same socket would read
                # the wrong reply; reconnect before the next attempt
                try:
                    self._reconnect()
                except OSError:
                    pass   # next attempt's send will surface it
                raise
        if self.retry is None:
            return attempt()
        return self.retry.run(attempt, site=site)

    def set(self, key: str, value: bytes,
            timeout: Optional[float] = None) -> None:
        self._resilient(
            "store.set",
            lambda: self._call("set", key.encode(), value,
                               sock_timeout=timeout))

    def get(self, key: str,
            timeout: Optional[float] = None) -> Optional[bytes]:
        r = self._resilient(
            "store.get",
            lambda: self._call("get", key.encode(),
                               sock_timeout=timeout))
        return r[1] if r[0] == b"ok" else None

    def add(self, key: str, amount: int = 1) -> int:
        # NOTE: add is retried for connectivity, not idempotency — a
        # reply lost to a reconnect may double-apply the increment.
        # Every caller treats the counter as an allocator of unique /
        # monotonic values (barrier arrivals excepted, which never
        # share a socket failure with a healthy barrier), so a skipped
        # value is safe where a dead client socket is not.
        r = self._resilient(
            "store.set",
            lambda: self._call("add", key.encode(), str(amount).encode()))
        return int(r[1])

    def delete(self, key: str) -> bool:
        r = self._resilient(
            "store.set", lambda: self._call("delete", key.encode()))
        return r[0] == b"ok"

    def compare_set(self, key: str, expect: bytes, value: bytes) -> bool:
        r = self._resilient(
            "store.set",
            lambda: self._call("cas", key.encode(), expect, value))
        if r[0] == b"ok":
            return True
        # Reconnect idempotency: if an earlier attempt applied but its
        # reply died with the socket, the retried CAS sees expect-
        # mismatch with the key already holding OUR value — that is a
        # success, not a conflict (lease renewal chains CAS on the
        # previous value, so a ghost write must not drop the lease).
        return len(r) > 1 and r[1] == value and value != expect

    def keys(self, prefix: str = "") -> list:
        r = self._resilient(
            "store.get", lambda: self._call("list", prefix.encode()))
        return [k.decode() for k in r[1:]]

    def wait(self, key: str, timeout: Optional[float] = None) -> bytes:
        # The server holds the request for up to `timeout`, so the client
        # socket must outlive the server-side wait or the reply would land
        # in the buffer after a socket timeout and desynchronize the
        # request/response stream for every later call.
        server_timeout = timeout if timeout is not None else self.timeout
        r = self._call("wait", key.encode(), str(server_timeout).encode(),
                       sock_timeout=server_timeout + 10.0)
        if r[0] != b"ok":
            raise TimeoutError(f"store key {key!r} not set in time")
        return r[1]

    def barrier(self, name: str, world_size: int,
                timeout: Optional[float] = None) -> None:
        """All-process barrier via an arrival counter + release key."""
        n = self.add(f"__barrier/{name}/count", 1)
        if n == world_size:
            self.set(f"__barrier/{name}/go", b"1")
        self.wait(f"__barrier/{name}/go", timeout)

    def close(self) -> None:
        try:
            self._sock.close()
        finally:
            if self._server is not None:
                self._server.shutdown()
                self._server.server_close()
                self._server = None
            if self._native_server is not None:
                self._native_server.close()
                self._native_server = None


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]
