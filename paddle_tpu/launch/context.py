"""Launcher context: CLI args + environment + device detection.

Reference: python/paddle/distributed/launch/context/ (args parsing, Node
device detection) and the PADDLE_* env protocol set in
controllers/controller.py.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import socket
from typing import List, Optional


@dataclasses.dataclass
class Context:
    script: str = ""
    script_args: List[str] = dataclasses.field(default_factory=list)
    nnodes: int = 1                       # max (= target) node count
    nnodes_min: Optional[int] = None      # elastic: accept >= this many
    nproc_per_node: int = 1
    master: Optional[str] = None          # host:port of rendezvous store
    rank: int = -1                        # node rank; -1 = assigned by master
    job_id: str = "default"
    log_dir: str = "log"
    elastic_level: int = 0                # 0=off, 1=restart on failure
    elastic_timeout: float = 30.0
    max_restarts: int = 3
    devices: Optional[str] = None         # visible device ids (CPU tests)
    host: str = dataclasses.field(default_factory=socket.gethostname)

    @property
    def max_world_size(self) -> int:
        """Upper bound from the CLI; the ACTUAL world size after an elastic
        settle is len(frozen membership) * nproc_per_node (controller)."""
        return self.nnodes * self.nproc_per_node

    @property
    def min_nodes(self) -> int:
        return self.nnodes if self.nnodes_min is None else self.nnodes_min


def _parse_nnodes(value) -> tuple:
    """``--nnodes 2`` → (2, 2); ``--nnodes 1:4`` → (1, 4) (reference elastic
    range syntax: python/paddle/distributed/launch/context/args_envs.py)."""
    s = str(value)
    if ":" in s:
        lo, hi = s.split(":", 1)
        lo, hi = int(lo), int(hi)
        if not 1 <= lo <= hi:
            raise ValueError(f"bad --nnodes range {s!r}")
        return lo, hi
    n = int(s)
    return n, n


def parse_args(argv: Optional[List[str]] = None) -> Context:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.launch",
        description="paddle_tpu distributed launcher (fleetrun parity)")
    p.add_argument("--nnodes", type=str,
                   default=os.environ.get("PADDLE_NNODES", "1"),
                   help="node count, or MIN:MAX for an elastic range")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="processes per node; default 1 (a TPU host drives "
                        "all local chips from one process)")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="host:port of the rendezvous store (node rank 0)")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", -1)))
    p.add_argument("--job_id", default=os.environ.get("PADDLE_JOB_ID",
                                                      "default"))
    p.add_argument("--log_dir", default="log")
    p.add_argument("--elastic_level", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_LEVEL", 0)))
    p.add_argument("--elastic_timeout", type=float,
                   default=float(os.environ.get("PADDLE_ELASTIC_TIMEOUT", 30)))
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--devices", default=None)
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    a = p.parse_args(argv)
    nmin, nmax = _parse_nnodes(a.nnodes)
    return Context(
        script=a.script, script_args=a.script_args, nnodes=nmax,
        nnodes_min=nmin,
        nproc_per_node=a.nproc_per_node or 1, master=a.master, rank=a.rank,
        job_id=a.job_id, log_dir=a.log_dir, elastic_level=a.elastic_level,
        elastic_timeout=a.elastic_timeout, max_restarts=a.max_restarts,
        devices=a.devices)
