"""Restart-based elastic manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py — etcd
membership with heartbeats; on membership change within elastic_timeout the
job's processes are killed and relaunched with recomputed ranks. State
continuity relies on user checkpoints (paddle_tpu.ckpt resume), exactly as
in the reference; on TPU the same path also covers preemption (SIGTERM from
the scheduler → graceful stop → relaunch on the surviving slice).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .store import TCPStore


class ElasticManager:
    """Heartbeat this node into the store and watch peer liveness."""

    def __init__(self, store: TCPStore, job_id: str, node_rank: int,
                 nnodes: int, timeout: float = 30.0,
                 heartbeat_period: float = 2.0, generation: int = 0):
        self.store = store
        self.job_id = job_id
        self.node_rank = node_rank
        self.nnodes = nnodes
        self.timeout = timeout
        self.heartbeat_period = heartbeat_period
        self.generation = generation
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at: Optional[float] = None

    def _key(self, rank: int) -> str:
        # generation-scoped: a relaunched (possibly shrunk) cluster must not
        # read the dead generation's stale heartbeats
        return f"elastic/{self.job_id}/gen{self.generation}/hb/{rank}"

    # -- scale-up (reference: fleet elastic manager relaunches on ANY
    # membership change, node-join included) -------------------------------

    @staticmethod
    def _join_key(job_id: str, generation: int) -> str:
        # generation-scoped so a request consumed by round g's relaunch can
        # never re-trigger a restart at round g+1
        return f"elastic/{job_id}/gen{generation}/join_req"

    @classmethod
    def announce_join(cls, store: TCPStore, job_id: str,
                      generation: int) -> None:
        """Called by a node frozen OUT of the current round's membership:
        ask the healthy cluster to advance the round and re-admit us."""
        store.set(cls._join_key(job_id, generation),
                  repr(time.time()).encode())

    def join_requested(self) -> bool:
        """A frozen-out node wants in at this generation."""
        return self.store.get(
            self._join_key(self.job_id, self.generation)) is not None

    def start(self) -> None:
        self._started_at = time.time()
        self._thread = threading.Thread(target=self._beat, daemon=True,
                                        name="pdtpu-elastic-hb")
        self._thread.start()

    def _beat(self) -> None:
        while not self._stop.is_set():
            self.store.set(self._key(self.node_rank),
                           repr(time.time()).encode())
            self._stop.wait(self.heartbeat_period)

    def dead_nodes(self) -> list:
        """Ranks whose heartbeat is older than the timeout.

        A peer with NO heartbeat yet is only dead once the startup grace
        period (= timeout, measured from our own start()) has elapsed —
        otherwise a node still deploying its pod would trigger a spurious
        restart on every generation."""
        now = time.time()
        in_grace = (self._started_at is not None
                    and now - self._started_at <= self.timeout)
        dead = []
        for r in range(self.nnodes):
            raw = self.store.get(self._key(r))
            if raw is None:
                if not in_grace:
                    dead.append(r)
                continue
            try:
                fresh = now - float(raw) <= self.timeout
            except (TypeError, ValueError):
                # an unparsable heartbeat payload (corrupt store value,
                # torn write) means the node's liveness is unknowable —
                # treat it as dead rather than crash the watcher that
                # every OTHER node's recovery depends on
                fresh = False
            if not fresh:
                dead.append(r)
        return dead

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
