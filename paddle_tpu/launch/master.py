"""Rendezvous master: rank assignment + peer discovery over TCPStore.

Reference: python/paddle/distributed/launch/controllers/master.py
(HTTPMaster for static clusters, ETCDMaster for elastic). Here one
implementation covers both: node rank 0 embeds the store server; every node
registers, waits for the full membership list, and derives global ranks.
Elastic mode reuses the same store for heartbeats (elastic.py).
"""

from __future__ import annotations

import json
import time
from typing import List, Tuple

from .store import TCPStore, free_port


class Master:
    def __init__(self, ctx, generation: int = 0):
        self.ctx = ctx
        self.generation = generation
        endpoint = ctx.master or f"127.0.0.1:{free_port()}"
        timeout = max(60.0, ctx.elastic_timeout * 2)
        if ctx.nnodes == 1 or ctx.rank == 0 or ctx.master is None:
            self.store = TCPStore(endpoint, is_master=True, timeout=timeout)
        else:
            # With auto rank assignment (--rank -1) no node knows it is the
            # master, so the node whose address can bind the endpoint hosts
            # the store and everyone else connects (first-binder-wins; a
            # non-local or already-bound address raises OSError → client).
            try:
                self.store = TCPStore(endpoint, is_master=True,
                                      timeout=timeout)
            except OSError:
                self.store = TCPStore(endpoint, is_master=False,
                                      timeout=timeout)

    def _key(self, name: str) -> str:
        return f"job/{self.ctx.job_id}/gen{self.generation}/{name}"

    def rendezvous(self) -> Tuple[int, List[str]]:
        """Register this node, wait for everyone, return
        (node_rank, all-node host list in rank order)."""
        ctx = self.ctx
        if ctx.nnodes == 1:
            return 0, [ctx.host]
        seq = self.store.add(self._key("joined"), 1) - 1
        node_rank = ctx.rank if ctx.rank >= 0 else seq
        info = json.dumps({"host": ctx.host, "nproc": ctx.nproc_per_node})
        self.store.set(self._key(f"node/{node_rank}"), info.encode())
        # wait for full membership
        deadline = time.monotonic() + self.store.timeout
        while True:
            nodes = self.store.keys(self._key("node/"))
            if len(nodes) >= ctx.nnodes:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rendezvous: {len(nodes)}/{ctx.nnodes} nodes joined")
            time.sleep(0.1)
        hosts = []
        for r in range(ctx.nnodes):
            raw = self.store.wait(self._key(f"node/{r}"))
            hosts.append(json.loads(raw)["host"])
        return node_rank, hosts

    def close(self):
        self.store.close()
