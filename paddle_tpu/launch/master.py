"""Rendezvous master: rank assignment + peer discovery over TCPStore.

Reference: python/paddle/distributed/launch/controllers/master.py
(HTTPMaster for static clusters, ETCDMaster for elastic). Here one
implementation covers both: node rank 0 embeds the store server; every node
registers, waits for the full membership list, and derives global ranks.
Elastic mode reuses the same store for heartbeats (elastic.py).
"""

from __future__ import annotations

import json
import time
from typing import List, Tuple

from .store import TCPStore, free_port


class Master:
    def __init__(self, ctx, generation: int = 0):
        self.ctx = ctx
        self.generation = generation
        endpoint = ctx.master or f"127.0.0.1:{free_port()}"
        timeout = max(60.0, ctx.elastic_timeout * 2)
        if ctx.nnodes == 1 or ctx.rank == 0 or ctx.master is None:
            self.store = TCPStore(endpoint, is_master=True, timeout=timeout)
        else:
            # With auto rank assignment (--rank -1) no node knows it is the
            # master, so the node whose address can bind the endpoint hosts
            # the store and everyone else connects (first-binder-wins; a
            # non-local or already-bound address raises OSError → client).
            try:
                self.store = TCPStore(endpoint, is_master=True,
                                      timeout=timeout)
            except OSError:
                self.store = TCPStore(endpoint, is_master=False,
                                      timeout=timeout)

    def _key(self, name: str) -> str:
        return f"job/{self.ctx.job_id}/gen{self.generation}/{name}"

    def rendezvous(self) -> Tuple[int, List[str]]:
        """Register this node, wait for membership, return
        (effective node rank, all-node host list in rank order).

        With an elastic range (``--nnodes MIN:MAX``) the first joiner acts as
        the decider: it freezes membership as soon as MAX nodes joined, or —
        once the settle window (= elastic_timeout) closes — with any quorum
        of >= MIN nodes (reference: fleet elastic manager's etcd membership
        scaling, python/paddle/distributed/fleet/elastic/manager.py).  The
        frozen list is what every node derives its rank and world size from,
        so a shrink-after-failure relaunch converges on a consistent,
        smaller cluster instead of waiting for the dead node."""
        ctx = self.ctx
        if ctx.nnodes == 1:
            return 0, [ctx.host]
        seq = self.store.add(self._key("joined"), 1) - 1
        node_rank = ctx.rank if ctx.rank >= 0 else seq
        info = json.dumps({"host": ctx.host, "nproc": ctx.nproc_per_node,
                           "rank": node_rank})
        self.store.set(self._key(f"node/{node_rank}"), info.encode())
        nmin = ctx.min_nodes
        if seq == 0:
            deadline = time.monotonic() + self.store.timeout
            # An elastic range (MIN:MAX) settles with any >= MIN quorum once
            # the settle window closes — on a FRESH job this is what lets a
            # below-MAX cluster start at all (late nodes join via the
            # scale-up path: announce_join → round advance → bigger world),
            # and on a restart generation it is what lets survivors proceed
            # without the dead peer.  The window must outlast a HEALTHY
            # peer's restart path — dead-node detection (<= elastic_timeout)
            # + pod teardown grace (<= ~10s) + restart sleep — or a mere
            # worker crash would permanently shrink the cluster past nodes
            # that are alive.  A fixed-size job (MIN == MAX) always waits
            # for full membership.
            # NOT elastic (elastic_level 0): always wait for full
            # membership — no manager would ever re-admit a frozen-out node
            elastic_range = nmin < ctx.nnodes and ctx.elastic_level > 0
            settle = time.monotonic() + (ctx.elastic_timeout + 15.0
                                         if elastic_range else
                                         self.store.timeout)
            while True:
                nodes = self.store.keys(self._key("node/"))
                if len(nodes) >= ctx.nnodes:
                    break
                if len(nodes) >= nmin and time.monotonic() > settle:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"rendezvous: {len(nodes)}/{ctx.nnodes} nodes joined")
                time.sleep(0.1)
            members = [json.loads(self.store.wait(k))
                       for k in self.store.keys(self._key("node/"))]
            members.sort(key=lambda m: m["rank"])
            self.store.set(self._key("members"), json.dumps(members).encode())
        members = json.loads(self.store.wait(self._key("members")))
        ranks = [m["rank"] for m in members]
        if node_rank not in ranks:
            raise TimeoutError(
                f"node rank {node_rank} joined after membership froze "
                f"(members: {ranks}); rejoin at the next generation")
        return ranks.index(node_rank), [m["host"] for m in members]

    def close(self):
        self.store.close()
