"""Collective controller: build the job, deploy the pod, watch it.

Reference: python/paddle/distributed/launch/controllers/controller.py
(watch loop: child exit → fail or elastic restart) and
controllers/collective.py (collective job build). §3.5 call stack.
"""

from __future__ import annotations

import logging
import signal
import time

from .elastic import ElasticManager
from .job import Job, Pod, build_container
from .master import Master
from .store import free_port

logger = logging.getLogger("paddle_tpu.launch")


class CollectiveController:
    def __init__(self, ctx):
        self.ctx = ctx
        self.generation = 0

    BASE_PORT = 6170  # reference launcher's default trainer base port

    def _build_pod(self, master: Master, node_rank: int,
                   hosts: list) -> Pod:
        ctx = self.ctx
        world = ctx.world_size
        # one coordination endpoint for jax.distributed.initialize: port on
        # the store host, stable across the generation
        coord_host = master.store.endpoint.rsplit(":", 1)[0]
        coord_key = f"job/{ctx.job_id}/gen{self.generation}/coord"
        if node_rank == 0:
            coord = f"{coord_host}:{free_port()}"
            master.store.set(coord_key, coord.encode())
        else:
            coord = master.store.wait(coord_key).decode()
        endpoints = [f"{hosts[g // ctx.nproc_per_node]}:"
                     f"{self.BASE_PORT + g % ctx.nproc_per_node}"
                     for g in range(world)]
        pod = Pod()
        for local in range(ctx.nproc_per_node):
            g = node_rank * ctx.nproc_per_node + local
            pod.containers.append(
                build_container(ctx, g, local, world, coord, endpoints))
        return pod

    def run(self) -> int:
        ctx = self.ctx
        restarts = 0
        while True:
            master = Master(ctx, generation=self.generation)
            node_rank, hosts = master.rendezvous()
            pod = self._build_pod(master, node_rank, hosts)
            elastic = None
            if ctx.elastic_level > 0 and ctx.nnodes > 1:
                elastic = ElasticManager(master.store, ctx.job_id, node_rank,
                                         ctx.nnodes, ctx.elastic_timeout)
                elastic.start()

            stop_requested = {"flag": False}

            def _on_term(signum, frame):
                stop_requested["flag"] = True
                pod.stop(grace=15.0)

            prev = signal.signal(signal.SIGTERM, _on_term)
            try:
                pod.deploy()
                code = self._watch(pod, elastic, stop_requested)
            finally:
                signal.signal(signal.SIGTERM, prev)
                if elastic is not None:
                    elastic.stop()
                pod.stop()
                master.close()

            if code == 0 or stop_requested["flag"]:
                return 0 if stop_requested["flag"] else code
            if ctx.elastic_level > 0 and restarts < ctx.max_restarts:
                restarts += 1
                self.generation += 1
                logger.warning("job failed (code %s); elastic restart %d/%d",
                               code, restarts, ctx.max_restarts)
                time.sleep(1.0)
                continue
            return code

    def _watch(self, pod: Pod, elastic, stop_requested) -> int:
        """Poll containers (and, in elastic mode, peer heartbeats)."""
        while True:
            if stop_requested["flag"]:
                return 0
            if not pod.alive():
                return pod.join()
            if pod.failed():
                logger.error("container failed; tearing down pod")
                pod.stop()
                return pod.join() or 1
            if elastic is not None:
                dead = elastic.dead_nodes()
                if dead:
                    logger.error("peer node(s) %s lost; restarting", dead)
                    pod.stop()
                    pod.join()
                    return 1
            time.sleep(0.2)
