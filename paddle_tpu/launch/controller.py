"""Collective controller: build the job, deploy the pod, watch it.

Reference: python/paddle/distributed/launch/controllers/controller.py
(watch loop: child exit → fail or elastic restart) and
controllers/collective.py (collective job build). §3.5 call stack.
"""

from __future__ import annotations

import logging
import signal
import time

from .elastic import ElasticManager
from .job import Job, Pod, build_container
from .master import Master
from .store import free_port

logger = logging.getLogger("paddle_tpu.launch")


class CollectiveController:
    def __init__(self, ctx):
        self.ctx = ctx
        self.generation = 0

    BASE_PORT = 6170  # reference launcher's default trainer base port
    GROW = -2         # _watch sentinel: membership grew, relaunch bigger

    def _build_pod(self, master: Master, node_rank: int,
                   hosts: list) -> Pod:
        ctx = self.ctx
        # world size follows the FROZEN membership (elastic shrink may have
        # settled below ctx.nnodes), not the CLI maximum
        world = len(hosts) * ctx.nproc_per_node
        # one coordination endpoint for jax.distributed.initialize: port on
        # the store host, stable across the generation
        coord_host = master.store.endpoint.rsplit(":", 1)[0]
        coord_key = f"job/{ctx.job_id}/gen{self.generation}/coord"
        if node_rank == 0:
            coord = f"{coord_host}:{free_port()}"
            master.store.set(coord_key, coord.encode())
        else:
            coord = master.store.wait(coord_key).decode()
        endpoints = [f"{hosts[g // ctx.nproc_per_node]}:"
                     f"{self.BASE_PORT + g % ctx.nproc_per_node}"
                     for g in range(world)]
        pod = Pod()
        for local in range(ctx.nproc_per_node):
            g = node_rank * ctx.nproc_per_node + local
            pod.containers.append(
                build_container(ctx, g, local, world, coord, endpoints))
        return pod

    def run(self) -> int:
        ctx = self.ctx
        restarts = 0
        # ONE master/store for the controller's lifetime: the shared round
        # counter (below) and cross-generation rendezvous state must survive
        # generation changes, so the store cannot be torn down per attempt
        master = Master(ctx, generation=self.generation)
        round_key = f"job/{ctx.job_id}/round"
        master.store.compare_set(round_key, b"", b"0")
        self.generation = int(master.store.get(round_key))
        try:
            while True:
                master.generation = self.generation
                try:
                    node_rank, hosts = master.rendezvous()
                except TimeoutError as e:
                    # frozen out of this round (joined late) or quorum never
                    # formed; in elastic mode announce ourselves — a HEALTHY
                    # cluster sees the join request and advances the round
                    # (scale-up) — then wait for the next round
                    if ctx.elastic_level > 0 and restarts < ctx.max_restarts:
                        restarts += 1
                        self.generation = int(master.store.get(round_key))
                        ElasticManager.announce_join(
                            master.store, ctx.job_id, self.generation)
                        logger.warning(
                            "rendezvous at round %d failed (%s); join "
                            "announced, waiting for the next round",
                            self.generation, e)
                        self.generation = self._await_round_change(
                            master.store, round_key, self.generation)
                        continue
                    raise
                pod = self._build_pod(master, node_rank, hosts)
                elastic = None
                if ctx.elastic_level > 0 and ctx.nnodes > 1:
                    # even a world-1 job needs the manager: it is how a
                    # below-MAX cluster notices a joining node (scale-up)
                    elastic = ElasticManager(master.store, ctx.job_id,
                                             node_rank, len(hosts),
                                             ctx.elastic_timeout,
                                             generation=self.generation)
                    elastic.start()
                can_grow = len(hosts) < ctx.nnodes

                stop_requested = {"flag": False}

                def _on_term(signum, frame):
                    stop_requested["flag"] = True
                    pod.stop(grace=15.0)

                prev = signal.signal(signal.SIGTERM, _on_term)
                try:
                    pod.deploy()
                    code = self._watch(pod, elastic, stop_requested,
                                       can_grow)
                finally:
                    signal.signal(signal.SIGTERM, prev)
                    if elastic is not None:
                        elastic.stop()
                    pod.stop()

                if code == 0 or stop_requested["flag"]:
                    return 0 if stop_requested["flag"] else code
                if code == self.GROW:
                    # scale-up: a frozen-out node asked in — advance the
                    # shared round and re-rendezvous at the larger world.
                    # Not a failure: does not consume the restart budget.
                    self.generation = self._advance_round(
                        master.store, round_key, self.generation)
                    logger.warning(
                        "scale-up: node join requested; relaunching at "
                        "round %d with larger membership", self.generation)
                    time.sleep(0.5)
                    continue
                if ctx.elastic_level > 0 and restarts < ctx.max_restarts:
                    restarts += 1
                    self.generation = self._advance_round(
                        master.store, round_key, self.generation)
                    logger.warning(
                        "job failed (code %s); elastic restart %d/%d at "
                        "round %d", code, restarts, ctx.max_restarts,
                        self.generation)
                    time.sleep(1.0)
                    continue
                return code
        finally:
            master.close()

    @staticmethod
    def _advance_round(store, round_key: str, current: int) -> int:
        """Advance the SHARED round via CAS: only the first node's CAS
        lands; every other node's loses and it adopts the stored value, so
        divergent local views cannot split the job into disjoint
        rendezvous namespaces."""
        store.compare_set(round_key, str(current).encode(),
                          str(current + 1).encode())
        return int(store.get(round_key))

    @staticmethod
    def _await_round_change(store, round_key: str, current: int,
                            poll: float = 0.5) -> int:
        deadline = time.monotonic() + store.timeout
        while time.monotonic() < deadline:
            raw = store.get(round_key)
            if raw is not None and int(raw) != current:
                return int(raw)
            time.sleep(poll)
        raise TimeoutError(
            f"round never advanced past {current}; the active cluster is "
            "running without this node (scale-up rejoin requires the next "
            "membership change)")

    def _watch(self, pod: Pod, elastic, stop_requested,
               can_grow: bool = False) -> int:
        """Poll containers (and, in elastic mode, peer heartbeats and —
        below MAX membership — scale-up join requests)."""
        while True:
            if stop_requested["flag"]:
                return 0
            if not pod.alive():
                return pod.join()
            if pod.failed():
                logger.error("container failed; tearing down pod")
                pod.stop()
                return pod.join() or 1
            if elastic is not None:
                dead = elastic.dead_nodes()
                if dead:
                    logger.error("peer node(s) %s lost; restarting", dead)
                    pod.stop()
                    pod.join()
                    return 1
                if can_grow and elastic.join_requested():
                    logger.warning(
                        "node join requested; stopping pod to grow")
                    pod.stop()
                    pod.join()
                    return self.GROW
            time.sleep(0.2)
