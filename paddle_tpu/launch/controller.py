"""Collective controller: build the job, deploy the pod, watch it.

Reference: python/paddle/distributed/launch/controllers/controller.py
(watch loop: child exit → fail or elastic restart) and
controllers/collective.py (collective job build). §3.5 call stack.
"""

from __future__ import annotations

import logging
import signal
import time

from .elastic import ElasticManager
from .job import Job, Pod, build_container
from .master import Master
from .store import free_port

logger = logging.getLogger("paddle_tpu.launch")


class CollectiveController:
    def __init__(self, ctx):
        self.ctx = ctx
        self.generation = 0

    BASE_PORT = 6170  # reference launcher's default trainer base port

    def _build_pod(self, master: Master, node_rank: int,
                   hosts: list) -> Pod:
        ctx = self.ctx
        # world size follows the FROZEN membership (elastic shrink may have
        # settled below ctx.nnodes), not the CLI maximum
        world = len(hosts) * ctx.nproc_per_node
        # one coordination endpoint for jax.distributed.initialize: port on
        # the store host, stable across the generation
        coord_host = master.store.endpoint.rsplit(":", 1)[0]
        coord_key = f"job/{ctx.job_id}/gen{self.generation}/coord"
        if node_rank == 0:
            coord = f"{coord_host}:{free_port()}"
            master.store.set(coord_key, coord.encode())
        else:
            coord = master.store.wait(coord_key).decode()
        endpoints = [f"{hosts[g // ctx.nproc_per_node]}:"
                     f"{self.BASE_PORT + g % ctx.nproc_per_node}"
                     for g in range(world)]
        pod = Pod()
        for local in range(ctx.nproc_per_node):
            g = node_rank * ctx.nproc_per_node + local
            pod.containers.append(
                build_container(ctx, g, local, world, coord, endpoints))
        return pod

    def run(self) -> int:
        ctx = self.ctx
        restarts = 0
        # ONE master/store for the controller's lifetime: the shared round
        # counter (below) and cross-generation rendezvous state must survive
        # generation changes, so the store cannot be torn down per attempt
        master = Master(ctx, generation=self.generation)
        round_key = f"job/{ctx.job_id}/round"
        master.store.compare_set(round_key, b"", b"0")
        self.generation = int(master.store.get(round_key))
        try:
            while True:
                master.generation = self.generation
                try:
                    node_rank, hosts = master.rendezvous()
                except TimeoutError as e:
                    # frozen out of this round (joined late) or quorum never
                    # formed; in elastic mode wait for the round to advance
                    # and try again rather than crashing the node
                    if ctx.elastic_level > 0 and restarts < ctx.max_restarts:
                        restarts += 1
                        logger.warning(
                            "rendezvous at round %d failed (%s); waiting "
                            "for the next round", self.generation, e)
                        self.generation = self._await_round_change(
                            master.store, round_key, self.generation)
                        continue
                    raise
                pod = self._build_pod(master, node_rank, hosts)
                elastic = None
                if ctx.elastic_level > 0 and len(hosts) > 1:
                    elastic = ElasticManager(master.store, ctx.job_id,
                                             node_rank, len(hosts),
                                             ctx.elastic_timeout,
                                             generation=self.generation)
                    elastic.start()

                stop_requested = {"flag": False}

                def _on_term(signum, frame):
                    stop_requested["flag"] = True
                    pod.stop(grace=15.0)

                prev = signal.signal(signal.SIGTERM, _on_term)
                try:
                    pod.deploy()
                    code = self._watch(pod, elastic, stop_requested)
                finally:
                    signal.signal(signal.SIGTERM, prev)
                    if elastic is not None:
                        elastic.stop()
                    pod.stop()

                if code == 0 or stop_requested["flag"]:
                    return 0 if stop_requested["flag"] else code
                if ctx.elastic_level > 0 and restarts < ctx.max_restarts:
                    restarts += 1
                    # advance the SHARED round via CAS: only the first
                    # failing node increments; every other node's CAS loses
                    # and it adopts the stored value, so divergent local
                    # restart counts cannot split the job into disjoint
                    # rendezvous namespaces
                    g = self.generation
                    master.store.compare_set(round_key, str(g).encode(),
                                             str(g + 1).encode())
                    self.generation = int(master.store.get(round_key))
                    logger.warning(
                        "job failed (code %s); elastic restart %d/%d at "
                        "round %d", code, restarts, ctx.max_restarts,
                        self.generation)
                    time.sleep(1.0)
                    continue
                return code
        finally:
            master.close()

    @staticmethod
    def _await_round_change(store, round_key: str, current: int,
                            poll: float = 0.5) -> int:
        deadline = time.monotonic() + store.timeout
        while time.monotonic() < deadline:
            raw = store.get(round_key)
            if raw is not None and int(raw) != current:
                return int(raw)
            time.sleep(poll)
        raise TimeoutError(
            f"round never advanced past {current}; the active cluster is "
            "running without this node (scale-up rejoin requires the next "
            "membership change)")

    def _watch(self, pod: Pod, elastic, stop_requested) -> int:
        """Poll containers (and, in elastic mode, peer heartbeats)."""
        while True:
            if stop_requested["flag"]:
                return 0
            if not pod.alive():
                return pod.join()
            if pod.failed():
                logger.error("container failed; tearing down pod")
                pod.stop()
                return pod.join() or 1
            if elastic is not None:
                dead = elastic.dead_nodes()
                if dead:
                    logger.error("peer node(s) %s lost; restarting", dead)
                    pod.stop()
                    pod.join()
                    return 1
            time.sleep(0.2)
