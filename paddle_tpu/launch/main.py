"""CLI entry: ``python -m paddle_tpu.launch [opts] script.py [args]``.

Reference: python/paddle/distributed/launch/main.py (fleetrun alias).
"""

from __future__ import annotations

import logging
import sys
from typing import List, Optional

from .context import parse_args
from .controller import CollectiveController


def launch(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    ctx = parse_args(argv)
    return CollectiveController(ctx).run()


if __name__ == "__main__":
    sys.exit(launch())
