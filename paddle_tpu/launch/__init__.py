"""Distributed launcher (``python -m paddle_tpu.launch``).

Reference: python/paddle/distributed/launch/ — main.py (CLI),
controllers/collective.py + controllers/controller.py (watch loop),
controllers/master.py (HTTP/etcd rendezvous), job/pod.py, job/container.py
(process model), context/ (args + device detect);
python/paddle/distributed/fleet/elastic/manager.py (restart-based elastic).

TPU redesign: one training process per *host* (a TPU host owns all its local
chips through one jax runtime, unlike one-proc-per-GPU), rendezvous through a
small TCPStore (paddle_tpu.launch.store — the reference's TCPStore analogue;
jax.distributed's coordination service handles the device-level bootstrap),
restart-based elasticity with preemption watch (SIGTERM → checkpoint window
→ relaunch), per-rank log capture under --log_dir.
"""

from .context import Context, parse_args  # noqa: F401
from .controller import CollectiveController  # noqa: F401
from .job import Container, Job, Pod  # noqa: F401
from .main import launch  # noqa: F401
from .preempt import PreemptionGuard  # noqa: F401
from .store import TCPStore  # noqa: F401
