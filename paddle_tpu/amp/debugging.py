"""``paddle.amp.debugging`` parity: numeric-anomaly tooling.

Reference: python/paddle/amp/debugging.py (enable_tensor_checker /
disable_tensor_checker / TensorCheckerConfig / check_numerics — backed
by FLAGS_check_nan_inf per-op scans, SURVEY §5.2).

TPU mapping: the global checker toggles ``jax_debug_nans`` (XLA re-runs
the offending computation un-fused and raises at the op, which is the
reference's per-op scan capability); ``check_numerics`` is a value-level
probe usable in BOTH modes — eager raises immediately, traced code
routes through ``jax.debug.callback`` so the error surfaces host-side
with the user's tag.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics"]


@dataclass
class TensorCheckerConfig:
    enable: bool = True
    debug_mode: str = "check_nan_inf_and_abort"  # reference enum names
    output_dir: Optional[str] = None


_active: list = [None]


def enable_tensor_checker(config: Optional[TensorCheckerConfig] = None):
    config = config or TensorCheckerConfig()
    _active[0] = config
    jax.config.update("jax_debug_nans", bool(config.enable))
    return config


def disable_tensor_checker():
    _active[0] = None
    jax.config.update("jax_debug_nans", False)


def _raise_if_bad(n_nan, n_inf, message):
    if int(n_nan) or int(n_inf):
        raise FloatingPointError(
            f"check_numerics failed{': ' + message if message else ''} — "
            f"{int(n_nan)} NaN and {int(n_inf)} Inf values")


def check_numerics(x, message: str = "", raise_on_trace: bool = True):
    """Assert ``x`` is finite. Returns ``x`` so it can be inserted inline
    (``h = check_numerics(h, "after attn")``)."""
    xf = jnp.asarray(x).astype(jnp.float32)
    n_nan = jnp.sum(jnp.isnan(xf))
    n_inf = jnp.sum(jnp.isinf(xf))
    if isinstance(n_nan, jax.core.Tracer):
        if raise_on_trace:
            jax.debug.callback(_raise_if_bad, n_nan, n_inf, message,
                               ordered=False)
        return x
    _raise_if_bad(n_nan, n_inf, message)
    return x
