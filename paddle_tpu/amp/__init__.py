"""Automatic mixed precision (``paddle.amp`` parity).

Reference: python/paddle/amp/{auto_cast.py,grad_scaler.py} and the C++ cast
insertion in eager ad_funcs (paddle/fluid/eager/amp_utils.h).  On TPU the
native mixed-precision story is bf16 compute with fp32 master weights — no
loss scaling needed — but full fp16 GradScaler parity is provided for API
compatibility and for the rare fp16 use case.

- ``auto_cast(enable, dtype)``: a context that flips a process-global policy;
  layers consult it via ``amp_dtype()`` when constructing compute, and the
  Trainer casts activations at the jit boundary.  O1 behaviour (allow-list
  casting) is approximated the TPU-idiomatic way: params stay fp32 (or a
  master copy exists) and matmul/conv inputs are cast to the policy dtype.
- ``decorate(models, optimizers, level)``: O2 — casts model params to the
  low-precision dtype and turns on optimizer master weights
  (``multi_precision=True``), exactly the reference's O2 semantics.
- ``GradScaler``: dynamic loss scaling as a pure pytree transform usable
  inside compiled steps (scale/unscale/found_inf/update are all traceable).
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import convert_dtype

_policy = {"enable": False, "dtype": jnp.bfloat16, "level": "O1"}


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = dict(_policy)
    _policy.update(enable=enable, dtype=convert_dtype(dtype), level=level)
    try:
        yield
    finally:
        _policy.update(prev)


def amp_enabled() -> bool:
    return _policy["enable"]


def amp_dtype():
    return _policy["dtype"] if _policy["enable"] else jnp.float32


def white_cast(x):
    """Cast an array to the AMP compute dtype if AMP is on (allow-list ops)."""
    if _policy["enable"] and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(_policy["dtype"])
    return x


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False):
    """O2 decoration: cast params to ``dtype``, enable master weights.

    ``master_grad=True`` additionally promotes low-precision gradients to
    fp32 *before* grad clipping and the optimizer update (reference:
    paddle.amp.decorate's master_grad — there the cast happens in the eager
    accumulation hooks; here the optimizer applies it at the head of its
    pure update, so global-norm clipping sees fp32 too)."""
    d = convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    for m in model_list:
        m.astype(d)
    if optimizers is not None:
        opt_single = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if opt_single else list(optimizers)
        for o in opt_list:
            if master_weight is not False:
                o.multi_precision = True
            if master_grad:
                o.master_grad = True
        if single and opt_single:
            return models, optimizers
        return model_list, opt_list
    return models if single else model_list


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py).

    Functional usage inside a compiled step:
        scaled_loss = scaler.scale_value(loss, state)
        ... grads of scaled_loss ...
        grads, state = scaler.unscale_and_update(grads, state)
    ``state`` is a small pytree carried in the train state.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=2000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self.enable = enable
        self.init_loss_scaling = init_loss_scaling
        self.incr_ratio, self.decr_ratio = incr_ratio, decr_ratio
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n = decr_every_n_nan_or_inf
        self.dynamic = use_dynamic_loss_scaling
        # eager-parity state
        self._state = self.init_state()

    def init_state(self):
        return {"scale": jnp.asarray(self.init_loss_scaling, jnp.float32),
                "good_steps": jnp.zeros((), jnp.int32),
                "bad_steps": jnp.zeros((), jnp.int32)}

    # -- functional core ---------------------------------------------------

    def scale_value(self, loss, state=None):
        if not self.enable:
            return loss
        s = (state or self._state)["scale"]
        return loss * s.astype(loss.dtype)

    def unscale_and_update(self, grads, state=None):
        state = state or self._state
        if not self.enable:
            return grads, state
        scale = state["scale"]
        inv = 1.0 / scale
        grads = jax.tree.map(lambda g: g * inv.astype(g.dtype), grads)
        finite = jnp.asarray(True)
        for g in jax.tree.leaves(grads):
            finite = finite & jnp.all(jnp.isfinite(g.astype(jnp.float32)))
        if not self.dynamic:
            return grads, {**state, "found_inf": ~finite}
        good = jnp.where(finite, state["good_steps"] + 1, 0)
        bad = jnp.where(finite, 0, state["bad_steps"] + 1)
        grow = good >= self.incr_every_n_steps
        shrink = bad >= self.decr_every_n
        new_scale = jnp.where(grow, scale * self.incr_ratio, scale)
        new_scale = jnp.where(shrink, jnp.maximum(scale * self.decr_ratio, 1.0),
                              new_scale)
        new_state = {"scale": new_scale,
                     "good_steps": jnp.where(grow, 0, good),
                     "bad_steps": jnp.where(shrink, 0, bad),
                     "found_inf": ~finite}
        # zero non-finite grads so the (masked) optimizer step is a no-op
        grads = jax.tree.map(
            lambda g: jnp.where(finite, g, jnp.zeros_like(g)), grads)
        return grads, new_state

    # -- paddle eager surface ----------------------------------------------

    def scale(self, loss):
        return self.scale_value(loss, self._state)

    def step(self, optimizer):
        optimizer.step()

    def update(self):
        pass

    def unscale_(self, optimizer=None):
        if getattr(optimizer, "_eager_grads", None) is not None:
            optimizer._eager_grads, self._state = self.unscale_and_update(
                optimizer._eager_grads, self._state)

    def is_enable(self):
        return self.enable

    def state_dict(self):
        return {k: v for k, v in self._state.items()}

    def load_state_dict(self, d):
        self._state = dict(d)
from . import debugging  # noqa: F401


def is_bfloat16_supported(device=None) -> bool:
    """Reference: paddle.amp.is_bfloat16_supported — bf16 is the TPU
    native compute dtype (MXU) and jax's CPU mesh emulates it."""
    return True


def is_float16_supported(device=None) -> bool:
    """Reference: paddle.amp.is_float16_supported — fp16 storage/compute
    works through XLA on TPU (bf16 is preferred; see docs/MIGRATION.md)."""
    return True


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
