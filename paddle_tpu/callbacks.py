"""``paddle.callbacks`` namespace parity (reference exposes the hapi
callbacks at top level: paddle.callbacks.{Callback,ProgBarLogger,
ModelCheckpoint,EarlyStopping,LRScheduler,VisualDL,...})."""

from .hapi.callbacks import (Callback, CallbackList, EarlyStopping,  # noqa: F401
                             LogWriterCallback, LRScheduler,
                             ModelCheckpoint, ProgBarLogger,
                             ReduceLROnPlateau, SpeedMonitor, VisualDL)

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler", "ReduceLROnPlateau", "SpeedMonitor",
           "LogWriterCallback", "VisualDL"]
