"""SOT-lite: automatic conversion of plain Python control flow on traced
values into compiled ``lax.cond`` / ``lax.while_loop``.

Reference: python/paddle/jit/sot — the reference intercepts CPython
bytecode, builds a graph, and breaks/falls back where capture fails.  The
TPU-native analogue is source-level: ``to_static`` re-writes the decorated
function's AST so that

- ``if <tensor-pred>: ... else: ...`` becomes a ``lax.cond`` whose branch
  functions carry the assigned variables (paddle dy2static's
  ``convert_ifelse`` protocol, including its UndefinedVar placeholder
  semantics for one-sided assignments);
- ``while <tensor-pred>: ...`` becomes a ``lax.while_loop`` over the
  loop-carried variables;
- predicates that turn out CONCRETE at trace time keep exact Python
  semantics (only the taken branch runs, loops unroll) — the dispatch is
  by value, not by syntax;
- anything unconvertible (branch returns on one side only, break/continue,
  structure mismatch between branches, undefined loop carries) raises
  ``GraphBreakError`` mid-trace, which ``to_static`` surfaces with the
  file:line diagnostic (full_graph=True) or falls back to one eager call
  (full_graph=False), exactly like SOT's graph-break interpreter.

The transform is applied once at decoration time; failures to even parse
(no source, exotic syntax) silently leave the function untouched — the
pre-existing graph-break machinery then owns the behavior.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
import types
from typing import Callable, Tuple

import jax
from jax import lax

from .control_flow import GraphBreakError

__all__ = ["convert_control_flow"]


class _Undef:
    """paddle dy2static UndefinedVar analogue: placeholder for a name that
    is not bound at the branch point.  USING it (rather than overwriting
    it) raises — mirroring Python's UnboundLocalError, just later."""

    __slots__ = ()

    def __repr__(self):
        return "<undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "variable bound only inside an untaken branch was used "
            "(SOT-converted control flow; see paddle_tpu.jit.to_static)")

    __bool__ = __iter__ = __len__ = __getattr__ = __call__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __getitem__ = __array__ = _raise
    __float__ = __int__ = __index__ = _raise


_SOT_UNDEF = _Undef()


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _sot_if(pred, tfn, ffn, local_ns, names, dummy_ok, loc):
    vals = tuple(local_ns.get(n, _SOT_UNDEF) for n in names)
    if _is_tracer(pred):
        import jax.numpy as jnp
        # a name first bound INSIDE both branches (and never read before
        # its write) needs no real input — any placeholder threads through
        # lax.cond's operand slot and is overwritten by both branches
        vals = tuple(jnp.zeros(()) if (v is _SOT_UNDEF and n in dummy_ok)
                     else v for n, v in zip(names, vals))
        if any(v is _SOT_UNDEF for v in vals):
            missing = [n for n, v in zip(names, vals) if v is _SOT_UNDEF]
            raise GraphBreakError(
                f"graph break at {loc}: branch on a traced value where "
                f"variable(s) {missing} are only defined on one side; "
                "lax.cond needs both branches to produce every output. "
                "Define them before the if, or see to_static(full_graph=...)")
        try:
            return lax.cond(pred, lambda vs: tuple(tfn(*vs)),
                            lambda vs: tuple(ffn(*vs)), vals)
        except (TypeError, ValueError) as e:
            raise GraphBreakError(
                f"graph break at {loc}: auto-converted `if` could not "
                f"compile ({e})") from e
    return tfn(*vals) if pred else ffn(*vals)


def _sot_if_ret(pred, tfn, ffn, local_ns, names, dummy_ok, loc):
    """Value-form: both branches terminate in ``return``."""
    vals = tuple(local_ns.get(n, _SOT_UNDEF) for n in names)
    if _is_tracer(pred):
        import jax.numpy as jnp
        vals = tuple(jnp.zeros(()) if (v is _SOT_UNDEF and n in dummy_ok)
                     else v for n, v in zip(names, vals))
        if any(v is _SOT_UNDEF for v in vals):
            missing = [n for n, v in zip(names, vals) if v is _SOT_UNDEF]
            raise GraphBreakError(
                f"graph break at {loc}: branch on a traced value reads "
                f"undefined variable(s) {missing}")
        try:
            return lax.cond(pred, lambda vs: tfn(*vs), lambda vs: ffn(*vs),
                            vals)
        except (TypeError, ValueError) as e:
            raise GraphBreakError(
                f"graph break at {loc}: auto-converted `if/return` could "
                f"not compile ({e})") from e
    return tfn(*vals) if pred else ffn(*vals)


def _sot_while(cfn, bfn, local_ns, names, loc):
    vals = tuple(local_ns.get(n, _SOT_UNDEF) for n in names)
    undef = any(v is _SOT_UNDEF for v in vals)
    t = cfn(*vals)
    if _is_tracer(t):
        if undef:
            missing = [n for n, v in zip(names, vals) if v is _SOT_UNDEF]
            raise GraphBreakError(
                f"graph break at {loc}: traced `while` with loop-carried "
                f"variable(s) {missing} undefined before the loop")
        try:
            return lax.while_loop(lambda vs: cfn(*vs),
                                  lambda vs: tuple(bfn(*vs)), vals)
        except (TypeError, ValueError) as e:
            raise GraphBreakError(
                f"graph break at {loc}: auto-converted `while` could not "
                f"compile ({e}). lax.while_loop requires the body to keep "
                "every carried shape/dtype fixed") from e
    # concrete predicate: plain Python semantics (loop unrolls under trace)
    while t:
        vals = tuple(bfn(*vals))
        t = cfn(*vals)
    return vals


class _Names(ast.NodeVisitor):
    def __init__(self):
        self.stores, self.loads = set(), set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.stores.add(node.id)
        else:
            self.loads.add(node.id)

    def visit_AugAssign(self, node):
        # `y += 1` both reads and writes y
        if isinstance(node.target, ast.Name):
            self.loads.add(node.target.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.stores.add(node.name)   # nested defs bind a local name

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass  # lambda params are not enclosing-scope names


def _names(nodes) -> Tuple[set, set]:
    v = _Names()
    for n in (nodes if isinstance(nodes, (list, tuple)) else [nodes]):
        v.visit(n)
    return v.stores, v.loads


class _Blocker(ast.NodeVisitor):
    """Detects statements that make a block unconvertible: control escape,
    scope manipulation, or SIDE EFFECTS.  lax.cond traces BOTH branches,
    so a branch whose statements mutate state (attribute/subscript stores,
    bare call expressions) must NOT be captured — it would execute
    unconditionally (and can leak tracers into objects).  Such branches
    keep the graph-break behavior instead."""

    def __init__(self):
        self.blocked = False
        self.has_return = False

    def generic_visit(self, node):
        if isinstance(node, (ast.Break, ast.Continue, ast.Global,
                             ast.Nonlocal, ast.Yield, ast.YieldFrom,
                             ast.Await, ast.Try, ast.With, ast.Raise,
                             ast.Delete, ast.Import, ast.ImportFrom)):
            self.blocked = True
        if isinstance(node, ast.Expr) and not isinstance(
                node.value, ast.Constant):
            self.blocked = True   # bare expression: called for effect
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if not self._pure_target(t):
                    self.blocked = True
        if isinstance(node, ast.Return):
            self.has_return = True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested scopes keep their own control flow
        super().generic_visit(node)

    @staticmethod
    def _pure_target(t):
        if isinstance(t, ast.Name):
            return True
        if isinstance(t, (ast.Tuple, ast.List)):
            return all(_Blocker._pure_target(e) for e in t.elts)
        if isinstance(t, ast.Starred):
            return _Blocker._pure_target(t.value)
        return False  # Attribute / Subscript store: a side effect


def _scan(stmts):
    b = _Blocker()
    for s in stmts:
        b.visit(s)
    return b


def _terminates_in_return(stmts) -> bool:
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


def _helper_call_names(stmt):
    """For a generated ``_sot_*`` helper-call statement, the variable names
    it actually READS from ``locals()``: the names tuple minus the
    dummy-substitutable tuple.  None for ordinary statements."""
    val = getattr(stmt, "value", None) if isinstance(
        stmt, (ast.Assign, ast.Return)) else None
    if (isinstance(val, ast.Call) and isinstance(val.func, ast.Name)
            and val.func.id in ("_sot_if", "_sot_if_ret", "_sot_while")):
        tuples = [a for a in val.args
                  if isinstance(a, ast.Tuple)
                  and all(isinstance(e, ast.Constant) for e in a.elts)]
        if tuples:
            names = [e.value for e in tuples[0].elts]
            dummy = ([e.value for e in tuples[1].elts]
                     if len(tuples) > 1 else [])
            return [n for n in names if n not in dummy]
    return None


def _reads_before_write(stmts) -> set:
    """Names read before (or without) a preceding top-level write, in
    statement order.  Statement-granular: a read and write in the same
    statement (``y = y + 1``) counts as a read."""
    written, needs = set(), set()
    for s in stmts:
        hnames = _helper_call_names(s)
        if hnames is not None:
            needs |= {n for n in hnames if n not in written}
            st, _ = _names([s])
            written |= st
            continue
        st, ld = _names([s])
        needs |= {n for n in ld if n not in written}
        written |= st
    return needs


def _guaranteed_stores(stmts) -> set:
    """Names bound on EVERY path through these statements (top-level
    assigns only; conditional inner binds don't count)."""
    out = set()
    for s in stmts:
        if isinstance(s, ast.Assign):
            st, _ = _names([s])
            out |= st
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(s.target, ast.Name):
                out.add(s.target.id)
    return out


class _CFTransformer(ast.NodeTransformer):
    def __init__(self, fn_locals: set, filename: str):
        self.fn_locals = fn_locals
        self.filename = filename
        self.counter = 0
        self.changed = False

    # never descend into nested function/class definitions
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def _loc(self, node) -> str:
        return f"{self.filename}:{node.lineno}"

    def _make_fn(self, name, params, body_stmts, tail_return):
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=p) for p in params],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        body = list(body_stmts)
        if tail_return is not None:
            body = body + [tail_return]
        if not body:
            body = [ast.Pass()]
        return ast.FunctionDef(name=name, args=args, body=body,
                               decorator_list=[], returns=None,
                               type_params=[])

    def _names_tuple(self, names, ctx):
        return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                         ctx=ctx())

    def _call_helper(self, helper, test, tname, fname, names, dummy_ok,
                     loc):
        return ast.Call(
            func=ast.Name(id=helper, ctx=ast.Load()),
            args=[test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                           args=[], keywords=[]),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in dummy_ok],
                            ctx=ast.Load()),
                  ast.Constant(value=loc)],
            keywords=[])

    def visit_If(self, node):
        node = self.generic_visit(node)  # inner ifs/whiles first
        body_scan, else_scan = _scan(node.body), _scan(node.orelse)
        if body_scan.blocked or else_scan.blocked:
            return node
        i = self.counter
        self.counter += 1
        tname, fname = f"_sot_true_{i}", f"_sot_false_{i}"
        loc = self._loc(node)

        rb = (_reads_before_write(node.body)
              | _reads_before_write(node.orelse))

        if body_scan.has_return or else_scan.has_return:
            # value-form: only when BOTH branches terminate in return
            if not (_terminates_in_return(node.body)
                    and _terminates_in_return(node.orelse)):
                return node
            stores = (_names(node.body)[0] | _names(node.orelse)[0])
            params = sorted(stores & self.fn_locals)
            # each branch returns its own expression (no carry
            # passthrough): any name not read-before-write may be dummied
            dummy = sorted(set(params) - rb)
            t_fn = self._make_fn(tname, params, node.body, None)
            f_fn = self._make_fn(fname, params, node.orelse, None)
            ret = ast.Return(value=self._call_helper(
                "_sot_if_ret", node.test, tname, fname, params, dummy, loc))
            self.changed = True
            return [t_fn, f_fn, ret]

        stores = (_names(node.body)[0] | _names(node.orelse)[0])
        out = sorted(stores & self.fn_locals)
        if not out:
            return node  # side-effect-only branch: leave to graph-break
        # a name needs a REAL input value unless BOTH branches bind it on
        # every path and neither reads it first (then the untaken branch
        # never passes the input through)
        both = (_guaranteed_stores(node.body)
                & _guaranteed_stores(node.orelse))
        dummy = sorted((both - rb) & set(out))
        tail = ast.Return(value=self._names_tuple(out, ast.Load))
        t_fn = self._make_fn(tname, out, node.body, tail)
        f_fn = self._make_fn(fname, out, node.orelse, tail)
        assign = ast.Assign(
            targets=[self._names_tuple(out, ast.Store)],
            value=self._call_helper("_sot_if", node.test, tname, fname,
                                    out, dummy, loc))
        self.changed = True
        return [t_fn, f_fn, assign]

    def visit_While(self, node):
        node = self.generic_visit(node)
        if node.orelse:
            return node
        scan = _scan(node.body)
        if scan.blocked or scan.has_return:
            return node
        body_stores, _ = _names(node.body)
        _, test_loads = _names(node.test)
        carry = sorted((body_stores | (test_loads & self.fn_locals))
                       & self.fn_locals)
        if not carry:
            return node
        i = self.counter
        self.counter += 1
        cname, bname = f"_sot_cond_{i}", f"_sot_body_{i}"
        loc = self._loc(node)
        c_fn = self._make_fn(cname, carry, [ast.Return(value=node.test)],
                             None)
        b_fn = self._make_fn(
            bname, carry, node.body,
            ast.Return(value=self._names_tuple(carry, ast.Load)))
        assign = ast.Assign(
            targets=[self._names_tuple(carry, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="_sot_while", ctx=ast.Load()),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                               args=[], keywords=[]),
                      ast.Tuple(elts=[ast.Constant(value=n) for n in carry],
                                ctx=ast.Load()),
                      ast.Constant(value=loc)],
                keywords=[]))
        self.changed = True
        return [c_fn, b_fn, assign]


def convert_control_flow(fn: Callable) -> Tuple[Callable, bool]:
    """Return (converted_fn, changed).  On any structural obstacle the
    original function is returned unchanged."""
    bound_self = None
    target = fn
    if inspect.ismethod(fn):
        bound_self, target = fn.__self__, fn.__func__
    if not inspect.isfunction(target):
        return fn, False
    if hasattr(target, "__wrapped__"):
        # functools.wraps chain: getsource would return the INNER
        # function's source and the recompile would silently drop the
        # wrapper's behavior (and mismatch closure cells) — leave it alone
        return fn, False
    try:
        src = textwrap.dedent(inspect.getsource(target))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn, False
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn, False
    fdef.decorator_list = []

    # the function's own local names: parameters + every store in the body
    params = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args
                              + fdef.args.kwonlyargs)}
    if fdef.args.vararg:
        params.add(fdef.args.vararg.arg)
    if fdef.args.kwarg:
        params.add(fdef.args.kwarg.arg)
    body_stores, _ = _names(fdef.body)
    fn_locals = params | body_stores

    tr = _CFTransformer(fn_locals, inspect.getfile(target))
    # visit the body statements directly: the top-level def itself must not
    # trip the nested-scope guard
    new_body = []
    for stmt in fdef.body:
        res = tr.visit(stmt)
        if isinstance(res, list):
            new_body.extend(res)
        elif res is not None:
            new_body.append(res)
    fdef.body = new_body
    if not tr.changed:
        return fn, False
    ast.fix_missing_locations(tree)
    try:
        code = compile(tree, filename=f"<sot:{target.__name__}>",
                       mode="exec")
    except SyntaxError:
        return fn, False
    # globals: fall back to the ORIGINAL module namespace on missing keys,
    # so late-bound names (helpers defined after the decorator ran, the
    # function's own name for recursion) resolve at call time exactly like
    # the unconverted function — a plain dict snapshot would freeze them
    class _FallbackNS(dict):
        def __init__(self, base):
            super().__init__()
            self._base = base

        def __missing__(self, key):
            return self._base[key]

    ns = _FallbackNS(target.__globals__)
    # freevars: the re-compiled def has no closure cells; snapshot values
    if target.__closure__:
        for name, cell in zip(target.__code__.co_freevars,
                              target.__closure__):
            try:
                ns[name] = cell.cell_contents
            except ValueError:
                return fn, False  # unfilled cell (recursive def)
    ns.update(_sot_if=_sot_if, _sot_if_ret=_sot_if_ret,
              _sot_while=_sot_while, _SOT_UNDEF=_SOT_UNDEF)
    exec(code, ns)
    new_fn = ns[fdef.name]
    if target.__defaults__ is not None:
        new_fn.__defaults__ = target.__defaults__
    if target.__kwdefaults__:
        new_fn.__kwdefaults__ = dict(target.__kwdefaults__)
    if bound_self is not None:
        new_fn = types.MethodType(new_fn, bound_self)
    return new_fn, True
