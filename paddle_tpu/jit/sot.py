"""SOT-lite: automatic conversion of plain Python control flow on traced
values into compiled ``lax.cond`` / ``lax.while_loop``.

Reference: python/paddle/jit/sot — the reference intercepts CPython
bytecode, builds a graph, and breaks/falls back where capture fails.  The
TPU-native analogue is source-level: ``to_static`` re-writes the decorated
function's AST so that

- ``if <tensor-pred>: ... else: ...`` becomes a ``lax.cond`` whose branch
  functions carry the assigned variables (paddle dy2static's
  ``convert_ifelse`` protocol, including its UndefinedVar placeholder
  semantics for one-sided assignments);
- ``while <tensor-pred>: ...`` becomes a ``lax.while_loop`` over the
  loop-carried variables;
- ``for i in range(...)`` becomes ONE ``lax.while_loop`` when any bound
  is traced — so a new trip count does not retrace (the reference SOT's
  guard-cache goal, reached by making the bound a loop input);
  ``for x in <traced array>`` becomes a ``lax.scan`` over the leading
  axis;
- ``break`` / ``continue`` in while / range-for loops are lowered by a
  pre-pass into flag variables + guard ``if``s (the reference dy2static's
  convert_break_continue cond-flag transform), which then convert like
  hand-written control flow;
- predicates that turn out CONCRETE at trace time keep exact Python
  semantics (only the taken branch runs, loops unroll) — the dispatch is
  by value, not by syntax, and a predicate that BECOMES traced mid-unroll
  (a break flag fed by a traced comparison) hands the remaining
  iterations to a compiled while_loop;
- anything unconvertible (branch returns on one side only, structure
  mismatch between branches, undefined loop carries) raises
  ``GraphBreakError`` mid-trace, which ``to_static`` surfaces with the
  file:line diagnostic (full_graph=True) or falls back to one eager call
  (full_graph=False), exactly like SOT's graph-break interpreter.

The transform is applied once at decoration time; failures to even parse
(no source, exotic syntax) silently leave the function untouched — the
pre-existing graph-break machinery then owns the behavior.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
import types
from typing import Callable, Tuple

import jax
from jax import lax

from .control_flow import GraphBreakError

__all__ = ["convert_control_flow"]


class _Undef:
    """paddle dy2static UndefinedVar analogue: placeholder for a name that
    is not bound at the branch point.  USING it (rather than overwriting
    it) raises — mirroring Python's UnboundLocalError, just later."""

    __slots__ = ()

    def __repr__(self):
        return "<undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "variable bound only inside an untaken branch was used "
            "(SOT-converted control flow; see paddle_tpu.jit.to_static)")

    __bool__ = __iter__ = __len__ = __getattr__ = __call__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __getitem__ = __array__ = _raise
    __float__ = __int__ = __index__ = _raise


_SOT_UNDEF = _Undef()


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _sot_if(pred, tfn, ffn, local_ns, names, dummy_ok, loc):
    vals = tuple(local_ns.get(n, _SOT_UNDEF) for n in names)
    if _is_tracer(pred):
        import jax.numpy as jnp
        # a name first bound INSIDE both branches (and never read before
        # its write) needs no real input — any placeholder threads through
        # lax.cond's operand slot and is overwritten by both branches
        vals = tuple(jnp.zeros(()) if (v is _SOT_UNDEF and n in dummy_ok)
                     else v for n, v in zip(names, vals))
        if any(v is _SOT_UNDEF for v in vals):
            missing = [n for n, v in zip(names, vals) if v is _SOT_UNDEF]
            raise GraphBreakError(
                f"graph break at {loc}: branch on a traced value where "
                f"variable(s) {missing} are only defined on one side; "
                "lax.cond needs both branches to produce every output. "
                "Define them before the if, or see to_static(full_graph=...)")
        try:
            return lax.cond(pred, lambda vs: tuple(tfn(*vs)),
                            lambda vs: tuple(ffn(*vs)), vals)
        except (TypeError, ValueError) as e:
            raise GraphBreakError(
                f"graph break at {loc}: auto-converted `if` could not "
                f"compile ({e})") from e
    return tfn(*vals) if pred else ffn(*vals)


def _sot_if_ret(pred, tfn, ffn, local_ns, names, dummy_ok, loc):
    """Value-form: both branches terminate in ``return``."""
    vals = tuple(local_ns.get(n, _SOT_UNDEF) for n in names)
    if _is_tracer(pred):
        import jax.numpy as jnp
        vals = tuple(jnp.zeros(()) if (v is _SOT_UNDEF and n in dummy_ok)
                     else v for n, v in zip(names, vals))
        if any(v is _SOT_UNDEF for v in vals):
            missing = [n for n, v in zip(names, vals) if v is _SOT_UNDEF]
            raise GraphBreakError(
                f"graph break at {loc}: branch on a traced value reads "
                f"undefined variable(s) {missing}")
        try:
            return lax.cond(pred, lambda vs: tfn(*vs), lambda vs: ffn(*vs),
                            vals)
        except (TypeError, ValueError) as e:
            raise GraphBreakError(
                f"graph break at {loc}: auto-converted `if/return` could "
                f"not compile ({e})") from e
    return tfn(*vals) if pred else ffn(*vals)


def _sot_while(cfn, bfn, local_ns, names, loc):
    vals = tuple(local_ns.get(n, _SOT_UNDEF) for n in names)
    # Concrete predicates keep plain Python semantics (the loop unrolls
    # under trace) — but the predicate can BECOME traced mid-unroll (a
    # lowered `break` flag fed by a traced comparison), so the dispatch
    # re-checks every iteration and hands the REMAINING iterations to one
    # lax.while_loop at the transition.
    while True:
        t = cfn(*vals)
        if _is_tracer(t):
            if any(v is _SOT_UNDEF for v in vals):
                missing = [n for n, v in zip(names, vals)
                           if v is _SOT_UNDEF]
                raise GraphBreakError(
                    f"graph break at {loc}: traced `while` with "
                    f"loop-carried variable(s) {missing} undefined before "
                    "the loop")
            try:
                return lax.while_loop(lambda vs: cfn(*vs),
                                      lambda vs: tuple(bfn(*vs)), vals)
            except (TypeError, ValueError) as e:
                raise GraphBreakError(
                    f"graph break at {loc}: auto-converted `while` could "
                    f"not compile ({e}). lax.while_loop requires the body "
                    "to keep every carried shape/dtype fixed") from e
        if not t:
            return vals
        vals = tuple(bfn(*vals))


def _sot_not(x):
    return jax.numpy.logical_not(x) if _is_tracer(x) else (not x)


def _sot_or(a, b):
    if _is_tracer(a) or _is_tracer(b):
        return jax.numpy.logical_or(a, b)
    return a or b


def _sot_and(a, b):
    if _is_tracer(a) or _is_tracer(b):
        return jax.numpy.logical_and(a, b)
    return a and b


def _sot_and_lazy(a, bf):
    """Short-circuiting and: ``bf`` (a thunk) is NOT evaluated when ``a``
    is concretely false — a lowered-break while test must not re-run a
    side-effecting condition (walrus, iterator pop) after break fired."""
    if not _is_tracer(a) and not a:
        return False
    return _sot_and(a, bf())


def _sot_step_lt(i, hi, st):
    """range-style continuation test, concrete or traced, either sign.
    A traced step of 0 (where Python's range() would raise) terminates
    the loop immediately instead of spinning the device forever."""
    if _is_tracer(i) or _is_tracer(hi) or _is_tracer(st):
        import jax.numpy as jnp
        return jnp.where(st == 0, False,
                         jnp.where(st > 0, i < hi, i > hi))
    if st == 0:
        raise ValueError("range() arg 3 must not be zero")
    return i < hi if st > 0 else i > hi


def _sot_for_range(lo, hi, st, bfn, local_ns, names, loc):
    """``for i in range(lo, hi, st)`` (no break/continue — those were
    lowered to a while beforehand).  Concrete bounds keep Python
    semantics (the loop unrolls under trace); ANY traced bound lowers to
    one ``lax.while_loop`` whose trip count is an input — so calling the
    compiled function with a different ``n`` does NOT recompile (the
    reference SOT's guard-cache goal, reached jax-style by making the
    bound dynamic instead of guarding a specialization)."""
    vals = tuple(local_ns.get(n, _SOT_UNDEF) for n in names)
    traced = any(map(_is_tracer, (lo, hi, st)))
    if traced:
        if any(v is _SOT_UNDEF for v in vals):
            missing = [n for n, v in zip(names, vals) if v is _SOT_UNDEF]
            raise GraphBreakError(
                f"graph break at {loc}: traced `for` with loop-carried "
                f"variable(s) {missing} undefined before the loop")
        if isinstance(st, int) and st == 0:
            raise ValueError("range() arg 3 must not be zero")
        try:
            out = lax.while_loop(
                lambda c: _sot_step_lt(c[0], hi, st),
                lambda c: (c[0] + st,) + tuple(bfn(c[0], *c[1:])),
                (jax.numpy.asarray(lo),) + vals)
            return out[1:]
        except (TypeError, ValueError) as e:
            raise GraphBreakError(
                f"graph break at {loc}: auto-converted `for` could not "
                f"compile ({e})") from e
    for i in range(lo, hi, st):
        vals = tuple(bfn(i, *vals))
    return vals


def _sot_for_iter(it, bfn, local_ns, names, loc):
    """``for x in <iterable>``: jax arrays iterate via ONE ``lax.scan``
    over the leading axis (a traced array cannot be Python-iterated);
    everything else keeps Python semantics."""
    vals = tuple(local_ns.get(n, _SOT_UNDEF) for n in names)
    if _is_tracer(it):
        if any(v is _SOT_UNDEF for v in vals):
            missing = [n for n, v in zip(names, vals) if v is _SOT_UNDEF]
            raise GraphBreakError(
                f"graph break at {loc}: traced `for` with loop-carried "
                f"variable(s) {missing} undefined before the loop")
        try:
            out, _ = lax.scan(lambda c, x: (tuple(bfn(x, *c)), None),
                              vals, it)
            return out
        except (TypeError, ValueError) as e:
            raise GraphBreakError(
                f"graph break at {loc}: auto-converted `for` over a "
                f"traced array could not compile ({e})") from e
    for x in it:
        vals = tuple(bfn(x, *vals))
    return vals


class _Names(ast.NodeVisitor):
    def __init__(self):
        self.stores, self.loads = set(), set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.stores.add(node.id)
        else:
            self.loads.add(node.id)

    def visit_AugAssign(self, node):
        # `y += 1` both reads and writes y
        if isinstance(node.target, ast.Name):
            self.loads.add(node.target.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.stores.add(node.name)   # nested defs bind a local name

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass  # lambda params are not enclosing-scope names


def _names(nodes) -> Tuple[set, set]:
    v = _Names()
    for n in (nodes if isinstance(nodes, (list, tuple)) else [nodes]):
        v.visit(n)
    return v.stores, v.loads


class _Blocker(ast.NodeVisitor):
    """Detects statements that make a block unconvertible: control escape,
    scope manipulation, or SIDE EFFECTS.  lax.cond traces BOTH branches,
    so a branch whose statements mutate state (attribute/subscript stores,
    bare call expressions) must NOT be captured — it would execute
    unconditionally (and can leak tracers into objects).  Such branches
    keep the graph-break behavior instead."""

    def __init__(self):
        self.blocked = False
        self.has_return = False

    def generic_visit(self, node):
        if isinstance(node, (ast.Break, ast.Continue, ast.Global,
                             ast.Nonlocal, ast.Yield, ast.YieldFrom,
                             ast.Await, ast.Try, ast.With, ast.Raise,
                             ast.Delete, ast.Import, ast.ImportFrom)):
            self.blocked = True
        if isinstance(node, ast.Expr) and not isinstance(
                node.value, ast.Constant):
            self.blocked = True   # bare expression: called for effect
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if not self._pure_target(t):
                    self.blocked = True
        if isinstance(node, ast.Return):
            self.has_return = True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested scopes keep their own control flow
        super().generic_visit(node)

    @staticmethod
    def _pure_target(t):
        if isinstance(t, ast.Name):
            return True
        if isinstance(t, (ast.Tuple, ast.List)):
            return all(_Blocker._pure_target(e) for e in t.elts)
        if isinstance(t, ast.Starred):
            return _Blocker._pure_target(t.value)
        return False  # Attribute / Subscript store: a side effect


def _scan(stmts):
    b = _Blocker()
    for s in stmts:
        b.visit(s)
    return b


def _terminates_in_return(stmts) -> bool:
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


def _helper_call_names(stmt):
    """For a generated ``_sot_*`` helper-call statement, the variable names
    it actually READS from ``locals()``: the names tuple minus the
    dummy-substitutable tuple.  None for ordinary statements."""
    val = getattr(stmt, "value", None) if isinstance(
        stmt, (ast.Assign, ast.Return)) else None
    if (isinstance(val, ast.Call) and isinstance(val.func, ast.Name)
            and val.func.id in ("_sot_if", "_sot_if_ret", "_sot_while",
                                "_sot_for_range", "_sot_for_iter")):
        tuples = [a for a in val.args
                  if isinstance(a, ast.Tuple)
                  and all(isinstance(e, ast.Constant) for e in a.elts)]
        if tuples:
            names = [e.value for e in tuples[0].elts]
            dummy = ([e.value for e in tuples[1].elts]
                     if len(tuples) > 1 else [])
            return [n for n in names if n not in dummy]
    return None


def _reads_before_write(stmts) -> set:
    """Names read before (or without) a preceding top-level write, in
    statement order.  Statement-granular: a read and write in the same
    statement (``y = y + 1``) counts as a read."""
    written, needs = set(), set()
    for s in stmts:
        hnames = _helper_call_names(s)
        if hnames is not None:
            needs |= {n for n in hnames if n not in written}
            st, _ = _names([s])
            written |= st
            continue
        st, ld = _names([s])
        needs |= {n for n in ld if n not in written}
        written |= st
    return needs


def _guaranteed_stores(stmts) -> set:
    """Names bound on EVERY path through these statements (top-level
    assigns only; conditional inner binds don't count)."""
    out = set()
    for s in stmts:
        if isinstance(s, ast.Assign):
            st, _ = _names([s])
            out |= st
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(s.target, ast.Name):
                out.add(s.target.id)
    return out


class _BCFinder(ast.NodeVisitor):
    """break/continue bound to THIS loop level (not inside nested loops
    or nested function definitions) — the one boundary-rule visitor."""

    def __init__(self):
        self.has_brk = self.has_cont = False

    def visit_Break(self, node):
        self.has_brk = True

    def visit_Continue(self, node):
        self.has_cont = True

    def visit_While(self, node):     # inner loops own their bc
        pass

    def visit_For(self, node):
        pass

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _bc_flags(stmts):
    f = _BCFinder()
    for s in (stmts if isinstance(stmts, (list, tuple)) else [stmts]):
        f.visit(s)
    return f.has_brk, f.has_cont


def _has_loop_bc(stmts) -> bool:
    return any(_bc_flags(stmts))


def _assign_const(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=ast.Constant(value=value))


def _call_expr(fname, *args):
    return ast.Call(func=ast.Name(id=fname, ctx=ast.Load()),
                    args=list(args), keywords=[])


class _LowerBreakContinue(ast.NodeTransformer):
    """Pre-pass: rewrite ``break``/``continue`` in ``while`` loops (and
    ``for i in range(...)`` loops, first lowered to a while) into flag
    variables + guard ``if``s — the standard cond-flag transform the
    reference's dy2static applies (convert_break_continue).  The main
    _CFTransformer then converts the resulting plain ifs/whiles exactly
    like hand-written ones."""

    def __init__(self):
        self.counter = 0
        self.changed = False

    # break/continue never cross a function boundary
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def _guard(self, brk, cont):
        if brk and cont:
            return _call_expr("_sot_not",
                              _call_expr("_sot_or",
                                         ast.Name(id=brk, ctx=ast.Load()),
                                         ast.Name(id=cont, ctx=ast.Load())))
        flag = brk or cont
        return _call_expr("_sot_not", ast.Name(id=flag, ctx=ast.Load()))

    def _lower(self, stmts, brk, cont):
        out = []
        for idx, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(_assign_const(brk, True))
                return out            # rest is statically unreachable
            if isinstance(s, ast.Continue):
                out.append(_assign_const(cont, True))
                return out
            if isinstance(s, ast.If) and _has_loop_bc([s]):
                body = self._lower(s.body, brk, cont) or [ast.Pass()]
                orelse = (self._lower(s.orelse, brk, cont)
                          if s.orelse else [])
                out.append(ast.If(test=s.test, body=body, orelse=orelse))
                rest = self._lower(stmts[idx + 1:], brk, cont)
                if rest:
                    out.append(ast.If(test=self._guard(brk, cont),
                                      body=rest, orelse=[]))
                return out
            out.append(s)
        return out

    def _flags_for(self, body):
        i = self.counter
        self.counter += 1
        has_brk, has_cont = _bc_flags(body)
        return (f"_sot_brk_{i}" if has_brk else None,
                f"_sot_cont_{i}" if has_cont else None)

    def visit_While(self, node):
        node = self.generic_visit(node)     # inner loops first
        if node.orelse or not _has_loop_bc(node.body):
            return node
        if _names(node.test)[0]:
            # the test itself BINDS names (walrus): relocating it into
            # guards/thunks would swallow the binding — stay Python
            return node
        brk, cont = self._flags_for(node.body)
        body = self._lower(node.body, brk, cont)
        if cont:
            body = [_assign_const(cont, False)] + body
        test = node.test
        pre = []
        if brk:
            pre = [_assign_const(brk, False)]
            # lazy: after break fires, the ORIGINAL test (possibly
            # side-effecting — walrus, iterator pop) must not run again
            thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                                   kwonlyargs=[], kw_defaults=[],
                                   kwarg=None, defaults=[]),
                body=node.test)
            test = _call_expr("_sot_and_lazy", self._guard(brk, None),
                              thunk)
        self.changed = True
        return pre + [ast.While(test=test, body=body, orelse=[])]

    def visit_For(self, node):
        node = self.generic_visit(node)
        if (node.orelse or not _has_loop_bc(node.body)
                or not isinstance(node.target, ast.Name)):
            return node
        rng = node.iter
        if not (isinstance(rng, ast.Call) and isinstance(rng.func, ast.Name)
                and rng.func.id == "range" and not rng.keywords
                and 1 <= len(rng.args) <= 3):
            return node     # only range() fors get the while lowering
        i = self.counter    # reserve names before _flags_for bumps it
        lo, hi, st = _range_args(rng)
        ivar = f"_sot_i_{i}"
        # range() evaluates its bounds ONCE — hoist them into temps so
        # the per-iteration test/increment can't re-run user expressions
        hivar, stvar = f"_sot_hi_{i}", f"_sot_st_{i}"
        brk, cont = self._flags_for(node.body)
        body = self._lower(node.body, brk, cont)
        if cont:
            body = [_assign_const(cont, False)] + body
        # target binds at iteration top; increment runs OUTSIDE the
        # guards so `continue` still advances the index
        body = ([ast.Assign(targets=[ast.Name(id=node.target.id,
                                              ctx=ast.Store())],
                            value=ast.Name(id=ivar, ctx=ast.Load()))]
                + body
                + [ast.Assign(targets=[ast.Name(id=ivar, ctx=ast.Store())],
                              value=ast.BinOp(
                                  left=ast.Name(id=ivar, ctx=ast.Load()),
                                  op=ast.Add(),
                                  right=ast.Name(id=stvar,
                                                 ctx=ast.Load())))])
        test = _call_expr("_sot_step_lt",
                          ast.Name(id=ivar, ctx=ast.Load()),
                          ast.Name(id=hivar, ctx=ast.Load()),
                          ast.Name(id=stvar, ctx=ast.Load()))
        if brk:
            test = _call_expr("_sot_and", self._guard(brk, None), test)
        pre = [ast.Assign(targets=[ast.Name(id=ivar, ctx=ast.Store())],
                          value=lo),
               ast.Assign(targets=[ast.Name(id=hivar, ctx=ast.Store())],
                          value=hi),
               ast.Assign(targets=[ast.Name(id=stvar, ctx=ast.Store())],
                          value=st)]
        if brk:
            pre.append(_assign_const(brk, False))
        self.changed = True
        return pre + [ast.While(test=test, body=body, orelse=[])]


def _range_args(rng: ast.Call):
    """(lo, hi, step) AST expressions for a syntactic range() call."""
    if len(rng.args) == 1:
        return ast.Constant(value=0), rng.args[0], ast.Constant(value=1)
    if len(rng.args) == 2:
        return rng.args[0], rng.args[1], ast.Constant(value=1)
    return rng.args[0], rng.args[1], rng.args[2]


class _CFTransformer(ast.NodeTransformer):
    def __init__(self, fn_locals: set, filename: str):
        self.fn_locals = fn_locals
        self.filename = filename
        self.counter = 0
        self.changed = False
        self._live = set()   # names read after the statement being visited

    def transform_block(self, stmts, live_after):
        """Visit a statement list threading backward liveness: when a
        loop is converted, names its body stores that are read AFTER the
        loop must ride the carry (or the conversion is declined) — a
        read-before-write heuristic alone would hand back stale values."""
        out = []
        for idx, stmt in enumerate(stmts):
            rest_loads = (_names(stmts[idx + 1:])[1] if idx + 1 < len(stmts)
                          else set())
            self._live = rest_loads | live_after
            res = self.visit(stmt)
            if isinstance(res, list):
                out.extend(res)
            elif res is not None:
                out.append(res)
        return out

    # never descend into nested function/class definitions
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        return node

    def _loc(self, node) -> str:
        return f"{self.filename}:{node.lineno}"

    def _make_fn(self, name, params, body_stmts, tail_return):
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=p) for p in params],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        body = list(body_stmts)
        if tail_return is not None:
            body = body + [tail_return]
        if not body:
            body = [ast.Pass()]
        return ast.FunctionDef(name=name, args=args, body=body,
                               decorator_list=[], returns=None,
                               type_params=[])

    def _names_tuple(self, names, ctx):
        return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                         ctx=ctx())

    def _call_helper(self, helper, test, tname, fname, names, dummy_ok,
                     loc):
        return ast.Call(
            func=ast.Name(id=helper, ctx=ast.Load()),
            args=[test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                           args=[], keywords=[]),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in dummy_ok],
                            ctx=ast.Load()),
                  ast.Constant(value=loc)],
            keywords=[])

    def visit_If(self, node):
        live = self._live
        node.body = self.transform_block(node.body, live)
        node.orelse = self.transform_block(node.orelse, live)
        self._live = live
        body_scan, else_scan = _scan(node.body), _scan(node.orelse)
        if body_scan.blocked or else_scan.blocked:
            return node
        i = self.counter
        self.counter += 1
        tname, fname = f"_sot_true_{i}", f"_sot_false_{i}"
        loc = self._loc(node)

        rb = (_reads_before_write(node.body)
              | _reads_before_write(node.orelse))

        if body_scan.has_return or else_scan.has_return:
            # value-form: only when BOTH branches terminate in return
            if not (_terminates_in_return(node.body)
                    and _terminates_in_return(node.orelse)):
                return node
            stores = (_names(node.body)[0] | _names(node.orelse)[0])
            params = sorted(stores & self.fn_locals)
            # each branch returns its own expression (no carry
            # passthrough): any name not read-before-write may be dummied
            dummy = sorted(set(params) - rb)
            t_fn = self._make_fn(tname, params, node.body, None)
            f_fn = self._make_fn(fname, params, node.orelse, None)
            ret = ast.Return(value=self._call_helper(
                "_sot_if_ret", node.test, tname, fname, params, dummy, loc))
            self.changed = True
            return [t_fn, f_fn, ret]

        stores = (_names(node.body)[0] | _names(node.orelse)[0])
        out = sorted(stores & self.fn_locals)
        if not out:
            return node  # side-effect-only branch: leave to graph-break
        # a name needs a REAL input value unless BOTH branches bind it on
        # every path and neither reads it first (then the untaken branch
        # never passes the input through)
        both = (_guaranteed_stores(node.body)
                & _guaranteed_stores(node.orelse))
        dummy = sorted((both - rb) & set(out))
        tail = ast.Return(value=self._names_tuple(out, ast.Load))
        t_fn = self._make_fn(tname, out, node.body, tail)
        f_fn = self._make_fn(fname, out, node.orelse, tail)
        assign = ast.Assign(
            targets=[self._names_tuple(out, ast.Store)],
            value=self._call_helper("_sot_if", node.test, tname, fname,
                                    out, dummy, loc))
        self.changed = True
        return [t_fn, f_fn, assign]

    def visit_While(self, node):
        live = self._live
        _, test_loads = _names(node.test)
        inner_live = live | _names(node.body)[1] | test_loads
        node.body = self.transform_block(node.body, inner_live)
        self._live = live
        if node.orelse:
            return node
        if _names(node.test)[0]:
            return node   # walrus in test: cfn can't surface the binding
        scan = _scan(node.body)
        if scan.blocked or scan.has_return:
            return node
        body_stores, _ = _names(node.body)
        # carry = the genuinely loop-carried names: read-before-write in
        # the body (accumulators), read by the test, or read AFTER the
        # loop (live — must surface the final value).  Loop-LOCAL
        # temporaries (written before read each iteration, dead after)
        # stay local to the body function — threading them would demand
        # a pre-loop definition that Python never required.
        rbw = _reads_before_write(node.body)
        carry = sorted(((body_stores & (rbw | live))
                        | (test_loads & self.fn_locals))
                       & self.fn_locals)
        if not carry:
            return node
        i = self.counter
        self.counter += 1
        cname, bname = f"_sot_cond_{i}", f"_sot_body_{i}"
        loc = self._loc(node)
        c_fn = self._make_fn(cname, carry, [ast.Return(value=node.test)],
                             None)
        b_fn = self._make_fn(
            bname, carry, node.body,
            ast.Return(value=self._names_tuple(carry, ast.Load)))
        assign = ast.Assign(
            targets=[self._names_tuple(carry, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="_sot_while", ctx=ast.Load()),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                               args=[], keywords=[]),
                      ast.Tuple(elts=[ast.Constant(value=n) for n in carry],
                                ctx=ast.Load()),
                      ast.Constant(value=loc)],
                keywords=[]))
        self.changed = True
        return [c_fn, b_fn, assign]

    def visit_For(self, node):
        """``for <name> in range(...)`` → _sot_for_range (while_loop for
        traced bounds: one compilation serves every trip count);
        ``for <name> in <expr>`` → _sot_for_iter (lax.scan for traced
        arrays).  break/continue cases were already lowered to whiles by
        the pre-pass; anything else unrollable stays plain Python."""
        live = self._live
        inner_live = live | _names(node.body)[1]
        node.body = self.transform_block(node.body, inner_live)
        self._live = live
        if node.orelse or not isinstance(node.target, ast.Name):
            return node
        scan = _scan(node.body)
        if scan.blocked or scan.has_return:
            return node
        tgt = node.target.id
        if tgt in live:
            # Python binds the target after the loop; a traced conversion
            # cannot surface it — stay Python (loud graph-break if the
            # bounds then turn out traced, never a silently stale value)
            return node
        body_stores, _ = _names(node.body)
        # only genuine carries (see visit_While): loop temporaries stay
        # local to the body function
        rbw = _reads_before_write(node.body)
        carry = sorted(((body_stores & (rbw | live)) - {tgt})
                       & self.fn_locals)
        if not carry:
            return node
        i = self.counter
        self.counter += 1
        bname = f"_sot_forbody_{i}"
        loc = self._loc(node)
        b_fn = self._make_fn(
            bname, [tgt] + carry, node.body,
            ast.Return(value=self._names_tuple(carry, ast.Load)))
        common = [ast.Name(id=bname, ctx=ast.Load()),
                  ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                           args=[], keywords=[]),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in carry],
                            ctx=ast.Load()),
                  ast.Constant(value=loc)]
        rng = node.iter
        if (isinstance(rng, ast.Call) and isinstance(rng.func, ast.Name)
                and rng.func.id == "range" and not rng.keywords
                and 1 <= len(rng.args) <= 3):
            lo, hi, st = _range_args(rng)
            call = ast.Call(func=ast.Name(id="_sot_for_range",
                                          ctx=ast.Load()),
                            args=[lo, hi, st] + common, keywords=[])
        else:
            call = ast.Call(func=ast.Name(id="_sot_for_iter",
                                          ctx=ast.Load()),
                            args=[node.iter] + common, keywords=[])
        assign = ast.Assign(
            targets=[self._names_tuple(carry, ast.Store)], value=call)
        self.changed = True
        return [b_fn, assign]


def convert_control_flow(fn: Callable) -> Tuple[Callable, bool]:
    """Return (converted_fn, changed).  On any structural obstacle the
    original function is returned unchanged."""
    bound_self = None
    target = fn
    if inspect.ismethod(fn):
        bound_self, target = fn.__self__, fn.__func__
    if not inspect.isfunction(target):
        return fn, False
    if hasattr(target, "__wrapped__"):
        # functools.wraps chain: getsource would return the INNER
        # function's source and the recompile would silently drop the
        # wrapper's behavior (and mismatch closure cells) — leave it alone
        return fn, False
    try:
        src = textwrap.dedent(inspect.getsource(target))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn, False
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn, False
    fdef.decorator_list = []

    # the function's own local names: parameters + every store in the body
    params = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args
                              + fdef.args.kwonlyargs)}
    if fdef.args.vararg:
        params.add(fdef.args.vararg.arg)
    if fdef.args.kwarg:
        params.add(fdef.args.kwarg.arg)
    # pre-pass: break/continue → flag variables + guard ifs (while-ified
    # range fors), so the main transformer sees plain convertible loops
    bc = _LowerBreakContinue()
    fdef.body = [bc.visit(s) if not isinstance(s, list) else s
                 for s in fdef.body]
    flat = []
    for s in fdef.body:
        flat.extend(s if isinstance(s, list) else [s])
    fdef.body = flat
    ast.fix_missing_locations(fdef)   # pre-pass nodes need linenos

    body_stores, _ = _names(fdef.body)
    fn_locals = params | body_stores

    tr = _CFTransformer(fn_locals, inspect.getfile(target))
    # transform the body statements directly (the top-level def itself
    # must not trip the nested-scope guard), threading backward liveness
    fdef.body = tr.transform_block(fdef.body, set())
    if not (tr.changed or bc.changed):
        return fn, False
    ast.fix_missing_locations(tree)
    try:
        code = compile(tree, filename=f"<sot:{target.__name__}>",
                       mode="exec")
    except SyntaxError:
        return fn, False
    # globals: fall back to the ORIGINAL module namespace on missing keys,
    # so late-bound names (helpers defined after the decorator ran, the
    # function's own name for recursion) resolve at call time exactly like
    # the unconverted function — a plain dict snapshot would freeze them
    class _FallbackNS(dict):
        def __init__(self, base):
            super().__init__()
            self._base = base

        def __missing__(self, key):
            return self._base[key]

    ns = _FallbackNS(target.__globals__)
    # freevars: the re-compiled def has no closure cells; snapshot values
    if target.__closure__:
        for name, cell in zip(target.__code__.co_freevars,
                              target.__closure__):
            try:
                ns[name] = cell.cell_contents
            except ValueError:
                return fn, False  # unfilled cell (recursive def)
    ns.update(_sot_if=_sot_if, _sot_if_ret=_sot_if_ret,
              _sot_while=_sot_while, _SOT_UNDEF=_SOT_UNDEF,
              _sot_not=_sot_not, _sot_or=_sot_or, _sot_and=_sot_and,
              _sot_and_lazy=_sot_and_lazy, _sot_step_lt=_sot_step_lt,
              _sot_for_range=_sot_for_range, _sot_for_iter=_sot_for_iter)
    exec(code, ns)
    new_fn = ns[fdef.name]
    if target.__defaults__ is not None:
        new_fn.__defaults__ = target.__defaults__
    if target.__kwdefaults__:
        new_fn.__kwdefaults__ = dict(target.__kwdefaults__)
    if bound_self is not None:
        new_fn = types.MethodType(new_fn, bound_self)
    return new_fn, True
