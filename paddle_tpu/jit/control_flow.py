"""Trace-safe dynamic control flow for ``to_static`` (Dy2Static parity).

Reference: python/paddle/jit/sot (bytecode capture with graph-break
fallback) and python/paddle/static/nn/control_flow.py (cond / while_loop /
case / switch_case program ops).  The TPU-native design keeps jax.jit's
one-trace model and offers the reference's two coping strategies for
value-dependent Python control flow:

- explicit trace-safe surfaces: ``cond``/``while_loop``/``case``/
  ``switch_case`` lower to ``lax.cond``/``lax.while_loop``/``lax.switch``,
  so the branch/loop is part of the compiled program (the reference's
  ControlFlowOp path);
- graph-break handling in ``to_static``: a raw tensor-dependent ``if``
  raises jax's TracerBoolConversionError mid-trace.  ``full_graph=True``
  re-raises it as a GraphBreakError that names the offending user
  file:line and the fix; ``full_graph=False`` (the reference SOT default)
  falls back to eager execution of the whole call, like SOT's graph-break
  interpreter, with a one-time warning.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Optional, Sequence

import jax
from jax import lax

__all__ = ["cond", "while_loop", "case", "switch_case", "GraphBreakError"]


class GraphBreakError(RuntimeError):
    """A value-dependent Python branch was hit while tracing under
    ``to_static(full_graph=True)``."""


def cond(pred, true_fn: Callable, false_fn: Callable, *operands):
    """``paddle.static.nn.cond`` parity.

    Both the closure style (``cond(p, lambda: x + 1, lambda: x - 1)``) and
    the operand style (``cond(p, f, g, x)``) are supported; both branches
    must return pytrees of identical structure/shape (XLA compiles both).
    """
    return lax.cond(pred, true_fn, false_fn, *operands)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence):
    """``paddle.static.nn.while_loop`` parity over ``lax.while_loop``.

    ``cond_fn``/``body_fn`` take the loop vars positionally; ``body_fn``
    returns the same number of vars with unchanged shapes/dtypes (XLA's
    fixed-point requirement — the reference's while op allowed shape
    growth, which has no static-shape equivalent)."""
    vals = tuple(loop_vars)
    out = lax.while_loop(lambda vs: cond_fn(*vs),
                         lambda vs: tuple(body_fn(*vs)), vals)
    return list(out)


def case(pred_fn_pairs, default: Optional[Callable] = None):
    """``paddle.static.nn.case``: first predicate that is True wins.

    Lowers to nested ``lax.cond`` so every predicate may be a traced
    scalar; all branches are compiled."""
    pairs = list(pred_fn_pairs)
    if not pairs:
        if default is None:
            raise ValueError("case() needs at least one (pred, fn) pair or "
                             "a default")
        return default()
    if default is None:
        # reference semantics: last branch is the fallback
        *pairs, (_, default) = pairs

    def build(i):
        if i == len(pairs):
            return default()
        pred, fn = pairs[i]
        return lax.cond(pred, fn, lambda: build(i + 1))

    return build(0)


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None):
    """``paddle.static.nn.switch_case`` parity over ``lax.switch``.

    ``branch_fns`` may be a list of callables or (index, callable) pairs;
    out-of-range indices take ``default`` (reference semantics; lax.switch
    alone would clamp)."""
    if isinstance(branch_fns, dict):
        branch_fns = list(branch_fns.items())
    if branch_fns and isinstance(branch_fns[0], (tuple, list)):
        keyed = sorted((int(k), fn) for k, fn in branch_fns)
        keys = [k for k, _ in keyed]
        fns = [fn for _, fn in keyed]
    else:
        fns = list(branch_fns)
        keys = list(range(len(fns)))
    if default is None:
        default = fns[-1]
    import jax.numpy as jnp
    idx = jnp.asarray(branch_index)
    # map the sparse key set onto dense lax.switch slots; unmatched → default
    table = fns + [default]
    sel = jnp.full((), len(fns), jnp.int32)
    for slot, k in enumerate(keys):
        sel = jnp.where(idx == k, jnp.int32(slot), sel)
    return lax.switch(sel, table)


# ---------------------------------------------------------------------------
# graph-break interception for to_static
# ---------------------------------------------------------------------------

def _user_frame(tb, fn) -> str:
    """Best-effort file:line of the user frame that triggered the break
    (innermost traceback frame outside jax/paddle_tpu internals)."""
    import os
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax_dir = os.path.dirname(os.path.abspath(jax.__file__))
    loc = None
    while tb is not None:
        fname = tb.tb_frame.f_code.co_filename
        if not fname.startswith((pkg_dir, jax_dir)):
            loc = f"{fname}:{tb.tb_lineno}"
        tb = tb.tb_next
    return loc or f"<{getattr(fn, '__name__', 'function')}>"


def graph_break_message(loc: str) -> str:
    return (
        f"graph break: value-dependent Python control flow at {loc}. "
        "Under to_static the function is traced once, so a branch on a "
        "tensor value cannot run in Python. Fix: (a) use "
        "paddle_tpu.jit.cond / while_loop / case for data-dependent "
        "branching, (b) mark the driving argument static via "
        "static_argnums, or (c) pass full_graph=False to run this call "
        "eagerly (the reference SOT's graph-break fallback).")


def _sig_key(args, kwargs):
    """Hashable call signature (structure + array shapes/dtypes + scalar
    values) — the SOT guard key: one graph break for a signature sends
    every later call with that signature straight to eager, skipping the
    doomed (and expensive) retrace."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))

    def leaf_key(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return ("arr", tuple(x.shape), str(x.dtype))
        try:
            hash(x)
            return x
        except TypeError:
            return ("unhashable", type(x).__name__)

    return (treedef, tuple(leaf_key(leaf) for leaf in leaves))


def intercept_graph_breaks(fn: Callable, jitted: Callable,
                           full_graph: bool) -> Callable:
    """Wrap a jitted callable: on a graph break (TracerBoolConversionError
    from raw Python branching, or GraphBreakError from the SOT-lite
    converter's unconvertible cases) either raise a paddle-style
    GraphBreakError (full_graph=True) or fall back to eager calls of
    ``fn`` (full_graph=False), memoised per call signature."""
    import functools
    warned = []
    broken_sigs = set()

    @functools.wraps(fn) if hasattr(fn, "__name__") else (lambda f: f)
    def wrapper(*args, **kwargs):
        if broken_sigs:
            try:
                if _sig_key(args, kwargs) in broken_sigs:
                    return fn(*args, **kwargs)
            except TypeError:
                pass
        try:
            return jitted(*args, **kwargs)
        except (jax.errors.TracerBoolConversionError, GraphBreakError) as e:
            if isinstance(e, GraphBreakError):
                msg = str(e)
            else:
                msg = graph_break_message(_user_frame(e.__traceback__, fn))
            if full_graph:
                raise GraphBreakError(msg) from e
            if not warned:
                warned.append(True)
                warnings.warn(
                    f"to_static: {msg} — running eagerly "
                    "(full_graph=False).", stacklevel=2)
            try:
                broken_sigs.add(_sig_key(args, kwargs))
            except TypeError:
                pass
            return fn(*args, **kwargs)

    wrapper.lower = jitted.lower
    wrapper.eval_shape = getattr(jitted, "eval_shape", None)
    wrapper._jitted = jitted
    return wrapper
