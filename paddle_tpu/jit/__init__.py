"""Step compiler (``paddle.jit`` parity, TPU-first).

The reference's whole static-graph stack — ``@to_static`` SOT capture
(python/paddle/jit/sot), ProgramDesc/PIR, InterpreterCore scheduling, CINN
codegen (SURVEY.md §2.3) — collapses on TPU into ``jax.jit``: one trace, XLA
fusion/scheduling, compiled-once execution.  This module provides:

- ``to_static(fn)``: jax.jit with paddle-like surface (input_spec accepted
  and used for AOT lowering).
- ``TrainStep``: THE canonical training path.  Wraps (model, loss_fn,
  optimizer, scaler) into one donated, sharded, compiled step function:
  state -> state.  All parallelism (mesh axes, param partition specs, ZeRO
  sharding of optimizer state) is applied here.
- ``save``/``load``: AOT export of compiled functions via StableHLO
  (``paddle.jit.save``'s inference-graph role).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import random as prandom
from ..nn.layer import Layer, functional_call, raw_params, trainable_mask
from ..observability import _state as _obs_state
from ..resilience import _state as _rs_state
from ..observability.spans import span as _span
from . import control_flow
from .control_flow import (GraphBreakError, case, cond, switch_case,
                           while_loop)


class InputSpec:
    """``paddle.static.InputSpec`` parity.  Dynamic dims (None/-1) are not
    representable in XLA's static-shape model; AOT warm-up skips them."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def is_static(self) -> bool:
        return all(isinstance(d, int) and d >= 0 for d in self.shape)

    def to_shape_struct(self):
        from ..core import convert_dtype
        return jax.ShapeDtypeStruct(self.shape, convert_dtype(self.dtype))


def to_static(function=None, input_spec=None, full_graph=True, backend=None,
              donate_argnums=(), static_argnums=(),
              convert_control_flow=True):
    """``paddle.jit.to_static`` parity → jax.jit.

    With a fully-static ``input_spec`` the function is AOT-lowered and
    compiled immediately (the reference's program-capture step); dynamic
    dims fall back to lazy shape-specialised jit with a warning.

    ``convert_control_flow=True`` (default) applies the SOT-lite AST
    transform (reference: python/paddle/jit/sot): plain Python ``if`` /
    ``while`` on traced values are rewritten into ``lax.cond`` /
    ``lax.while_loop`` automatically; unconvertible patterns keep the
    graph-break diagnostic / eager-fallback behavior.
    """
    def deco(fn):
        if getattr(fn, "_pdtpu_not_to_static", False):
            return fn
        target = fn
        # SOT conversion is skipped for functions whose defining module
        # was registered via jit.ignore_module (the transform is local to
        # the decorated function, so the decoration site is the scope).
        # With enable_to_static(False) active at DECORATION time, the
        # transform and the eager AOT compile below are also skipped —
        # debugging mode must not mutate layer.forward or trigger XLA
        # (re-enabling later jits the unconverted function).
        skip_sot = (getattr(target, "__module__", None) in _IGNORED_MODULES
                    or not _TO_STATIC_ENABLED[0])
        if convert_control_flow and not skip_sot:
            from . import sot as _sot
            from ..nn.layer import Layer
            if isinstance(fn, Layer):
                converted, ok = _sot.convert_control_flow(fn.forward)
                if ok:
                    # instance attribute shadows the class method; hooks
                    # and __call__ plumbing stay intact
                    fn.forward = converted
            else:
                target, _ = _sot.convert_control_flow(fn)
        jitted = jax.jit(target, donate_argnums=donate_argnums,
                         static_argnums=static_argnums)
        if not isinstance(fn, type) and callable(fn) and hasattr(fn, "__name__"):
            functools.update_wrapper(jitted, fn, updated=[])
        if input_spec and _TO_STATIC_ENABLED[0]:
            specs = [s if isinstance(s, InputSpec) else InputSpec(*s)
                     for s in input_spec]
            if all(s.is_static() for s in specs):
                jitted.lower(*[s.to_shape_struct() for s in specs]).compile()
            else:
                import warnings
                warnings.warn(
                    "to_static input_spec has dynamic dims; XLA requires "
                    "static shapes — compiling lazily per concrete shape "
                    "instead", stacklevel=2)
        compiled = control_flow.intercept_graph_breaks(fn, jitted,
                                                       full_graph)

        # enable_to_static is a CALL-time switch (reference semantics:
        # flipping it off routes already-decorated functions to eager)
        site = f"to_static({getattr(fn, '__name__', type(fn).__name__)})"

        def dispatch(*args, **kwargs):
            if not _TO_STATIC_ENABLED[0]:
                return fn(*args, **kwargs)
            mon = _obs_state.MONITOR[0]
            if mon is not None:
                with mon.compile_site(site):
                    return compiled(*args, **kwargs)
            return compiled(*args, **kwargs)

        if callable(fn) and hasattr(fn, "__name__"):
            functools.update_wrapper(dispatch, fn, updated=[])
        dispatch._pdtpu_compiled = compiled
        return dispatch
    return deco(function) if function is not None else deco


# ---------------------------------------------------------------------------
# sharding helpers
# ---------------------------------------------------------------------------

def _spec_of(meta_partition, ndim) -> P:
    if meta_partition is None:
        return P()
    if isinstance(meta_partition, P):
        return meta_partition
    return P(*meta_partition)


ZERO_MIN_SIZE = 2048  # numel below which zero-sharding isn't worth the comm


def zero_shard_spec(spec: P, shape, axis_name: str, axis_size: int,
                    min_size: int = ZERO_MIN_SIZE) -> P:
    """ZeRO-style sharding: additionally shard over ``axis_name`` on the
    first dim that is divisible and not already sharded.

    This is how ZeRO-1/2/3 semantics (reference:
    dygraph_sharding_optimizer.py / group_sharded_stage3.py) map to GSPMD:
    the stage choreography (reduce-to-owner, broadcast, allgather/release)
    becomes a sharding annotation and XLA inserts the moving parts
    (SURVEY.md §7.2).  Small tensors stay replicated (the reference's
    segment_size bucketing serves the same purpose).
    """
    if axis_size <= 1:
        return spec
    n = 1
    for d in shape:
        n *= d
    if n < min_size:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    if any(axis_name in (e if isinstance(e, tuple) else (e,))
           for e in entries):
        # already ZeRO-sharded over this axis (e.g. the param spec passed
        # through stage-3 before the opt-state pass re-applies): sharding
        # twice is meaningless and an invalid NamedSharding.  Surfaced by
        # the MoE router gate (4096, 8) whose free dim-1 is divisible by
        # the axis size — llama params dodge it only because 'mp'
        # annotations occupy every dim.
        return spec
    for i, (dim, cur) in enumerate(zip(shape, entries)):
        if cur is None and dim % axis_size == 0:
            entries[i] = axis_name
            return P(*entries)
    return spec  # nothing divisible; leave replicated


def _named(mesh, spec, host=False):
    if host:
        return NamedSharding(mesh, spec, memory_kind="pinned_host")
    return NamedSharding(mesh, spec)


def _zero_over(spec, shape, axes, mesh):
    for ax in axes:
        spec = zero_shard_spec(spec, shape, ax, mesh.shape[ax])
    return spec


# ---------------------------------------------------------------------------
# TrainStep
# ---------------------------------------------------------------------------

class TrainStep:
    """Compiled, sharded training step.

    Usage::

        model = Llama(cfg)
        opt = optimizer.AdamW(learning_rate=sched, parameters=model.parameters())
        step = TrainStep(model, loss_fn, opt, mesh=topo.mesh)
        state = step.init_state(seed=0)
        state, metrics = step(state, batch)

    ``loss_fn(model, batch) -> scalar`` runs with parameters functionally
    swapped in, so inside it the model is called exactly like eager paddle
    code.  The whole step (fwd, bwd, clip, optimizer, scaler) is one XLA
    program with the state donated (in-place buffer reuse, reference:
    InterpreterCore inplace pass).
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 scaler=None, mesh: Optional[Mesh] = None,
                 batch_axes=("dp", "sharding"), batch_spec=None,
                 zero_stage: Optional[int] = None,
                 zero_axes=("dp", "sharding"),
                 extra_metrics: Optional[Callable] = None,
                 gradient_accumulation: Optional[bool] = None):
        from ..distributed.parallel import DataParallel
        from ..distributed.sharding import zero_offload_of, zero_stage_of
        self.model = model
        # DataParallel's no_sync() drives per-call accumulation; carrying
        # acc-grad buffers in the state costs memory, so they exist only
        # when the wrapper (or an explicit flag) asks for them
        self._accum = (isinstance(model, DataParallel)
                       if gradient_accumulation is None
                       else bool(gradient_accumulation))
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.scaler = scaler
        if mesh is None:
            # fleet.init() was called → pick up the global hybrid mesh
            # (paddle semantics: fleet state is process-global)
            from ..distributed import fleet as _fleet
            hcg = _fleet.get_hybrid_communicate_group()
            if hcg is not None:
                mesh = hcg.mesh
        self.mesh = mesh
        # group_sharded_parallel records the stage on the optimizer; an
        # explicit zero_stage argument (including 0 = force off) wins
        self.zero_stage = zero_stage_of(optimizer, zero_stage)
        self.zero_offload = zero_offload_of(optimizer)
        self.extra_metrics = extra_metrics
        if mesh is not None:
            present = [a for a in batch_axes if a in mesh.axis_names
                       and mesh.shape[a] > 1]
            self.batch_spec = batch_spec if batch_spec is not None else (
                P(tuple(present)) if present else P())
            self.zero_axes = [a for a in zero_axes if a in mesh.axis_names
                              and mesh.shape[a] > 1]
        else:
            self.batch_spec = P()
            self.zero_axes = []
        self._mask = trainable_mask(model)
        self._compiled = jax.jit(self._step, donate_argnums=(0,),
                                 static_argnums=(2,))
        self._site = f"TrainStep({type(model).__name__})"

    # -- sharding specs ----------------------------------------------------

    def param_specs(self) -> Dict[str, P]:
        meta = self.model.param_meta()
        params = raw_params(self.model)
        specs = {}
        for name, p in params.items():
            spec = _spec_of(meta[name].partition if name in meta else None, p.ndim)
            if self.zero_stage >= 3:
                spec = _zero_over(spec, p.shape, self.zero_axes, self.mesh)
            specs[name] = spec
        return specs

    def grad_specs(self, grads, param_specs) -> Dict[str, P]:
        """ZeRO-2+: gradients sharded like the optimizer states, so the
        grad all-reduce becomes a reduce-scatter (reference:
        GroupShardedOptimizerStage2 grad partitioning)."""
        if self.zero_stage < 2 or self.mesh is None:
            return {k: param_specs[k] for k in grads}
        return {k: _zero_over(param_specs[k], grads[k].shape,
                              self.zero_axes, self.mesh)
                for k in grads}

    def opt_state_specs(self, opt_state, param_specs) -> Any:
        """Optimizer slots/master weights: mirror param sharding; ZeRO>=1
        additionally shards them over the data axes."""
        def spec_for(path_name, leaf):
            base = param_specs.get(path_name, P())
            if self.zero_stage >= 1 and hasattr(leaf, "ndim") and leaf.ndim > 0:
                base = _zero_over(base, leaf.shape, self.zero_axes, self.mesh)
            return base

        out = {}
        for slot, val in opt_state.items():
            if isinstance(val, dict):
                out[slot] = {k: spec_for(k, v) if v is not None else None
                             for k, v in val.items()}
            else:
                out[slot] = P()
        return out

    # -- state -------------------------------------------------------------

    def init_state(self, seed: int = 0) -> Dict[str, Any]:
        params = raw_params(self.model)
        opt_state = self.optimizer.init(params)
        state = {"params": params, "opt": opt_state,
                 "step": jnp.zeros((), jnp.int32),
                 "rng": jax.random.key(seed)}
        if self._accum:
            state["acc_grads"] = {
                k: jnp.zeros_like(v) for k, v in params.items()
                if self._mask.get(k, True)}
        if self.scaler is not None and self.scaler.enable:
            state["scaler"] = self.scaler.init_state()
            if self._accum:
                state["scaler"]["acc_found_inf"] = jnp.asarray(False)
        return self.shard_state(state)

    def abstract_state(self) -> Dict[str, Any]:
        """Abstract (ShapeDtypeStruct) analogue of
        ``init_state()+shard_state()`` for AOT lowering: every leaf carries
        its shape, dtype, and target sharding, but nothing materialises.
        Works with ``nn.meta_init()``-constructed models, so a 70B step can
        be compiled and memory-analysed on a host that could never hold it
        (tools/memproof.py; SURVEY §6 HBM-highwater validation)."""
        if self.mesh is None:
            raise ValueError("abstract_state requires a mesh")
        pspecs = self.param_specs()
        params = raw_params(self.model)

        def struct(leaf, spec, host=False):
            return jax.ShapeDtypeStruct(
                tuple(leaf.shape), leaf.dtype,
                sharding=_named(self.mesh, spec, host=host))

        aparams = {k: struct(v, pspecs[k]) for k, v in params.items()}
        opt_abs = jax.eval_shape(self.optimizer.init, aparams)
        ospecs = self.opt_state_specs(opt_abs, pspecs)
        host = self.zero_offload
        opt = {}
        for slot, val in opt_abs.items():
            if isinstance(val, dict):
                opt[slot] = {k: (struct(v, ospecs[slot][k], host=host)
                                 if v is not None else None)
                             for k, v in val.items()}
            else:
                opt[slot] = struct(val, P())
        rng = jax.eval_shape(lambda: jax.random.key(0))
        state = {"params": aparams, "opt": opt,
                 "step": jax.ShapeDtypeStruct((), jnp.int32,
                                              sharding=_named(self.mesh, P())),
                 "rng": jax.ShapeDtypeStruct(rng.shape, rng.dtype,
                                             sharding=_named(self.mesh, P()))}
        if self._accum:
            gspecs = self.grad_specs(
                {k: v for k, v in aparams.items()
                 if self._mask.get(k, True)}, pspecs)
            state["acc_grads"] = {
                k: struct(aparams[k], gspecs[k]) for k in gspecs}
        if self.scaler is not None and self.scaler.enable:
            sc = jax.eval_shape(self.scaler.init_state)
            state["scaler"] = jax.tree.map(
                lambda v: jax.ShapeDtypeStruct(
                    v.shape, v.dtype, sharding=_named(self.mesh, P())), sc)
            if self._accum:
                state["scaler"]["acc_found_inf"] = jax.ShapeDtypeStruct(
                    (), jnp.bool_, sharding=_named(self.mesh, P()))
        return state

    def shard_state(self, state):
        if self.mesh is None:
            return state
        pspecs = self.param_specs()
        ospecs = self.opt_state_specs(state["opt"], pspecs)
        with self.mesh:
            state["params"] = {
                k: jax.device_put(v, _named(self.mesh, pspecs[k]))
                for k, v in state["params"].items()}
            new_opt = {}
            # offload: optimizer states live in pinned host memory; XLA
            # inserts the transfers around the sharded update
            host = self.zero_offload
            for slot, val in state["opt"].items():
                if isinstance(val, dict):
                    new_opt[slot] = {
                        k: (jax.device_put(v, _named(self.mesh,
                                                     ospecs[slot][k],
                                                     host=host))
                            if v is not None else None)
                        for k, v in val.items()}
                else:
                    new_opt[slot] = jax.device_put(val, _named(self.mesh, P()))
            state["opt"] = new_opt
            if "acc_grads" in state:
                gspecs = self.grad_specs(state["acc_grads"], pspecs)
                state["acc_grads"] = {
                    k: jax.device_put(v, _named(self.mesh, gspecs[k]))
                    for k, v in state["acc_grads"].items()}
            state["step"] = jax.device_put(state["step"], _named(self.mesh, P()))
            # the rng key must be a mesh-replicated global array too —
            # otherwise a checkpoint-restored key stays committed to one
            # device and conflicts with the mesh-sharded state under jit.
            # device_put rejects typed key arrays on multi-process
            # shardings, so replicate the raw key_data and re-wrap.
            # (rng-less states — e.g. params/opt-only dicts fed through
            # Engine.load — pass through untouched)
            if "rng" in state:
                impl = str(jax.random.key_impl(state["rng"]))
                key_data = jax.device_put(jax.random.key_data(state["rng"]),
                                          _named(self.mesh, P()))
                state["rng"] = jax.random.wrap_key_data(key_data, impl=impl)
        return state

    # -- the step ----------------------------------------------------------

    def _loss(self, train_params, frozen, batch, key, scaler_state):
        from ..nn.layer import _swapped_params, _train_mode
        params = {**frozen, **train_params}
        with jax.named_scope("forward"), _swapped_params(self.model, params), \
                _train_mode(self.model, True), prandom.rng_scope(key):
            loss = self.loss_fn(self.model, batch)
        scaled = loss
        if self.scaler is not None and self.scaler.enable:
            scaled = self.scaler.scale_value(loss, scaler_state)
        return scaled, loss

    def _step(self, state, batch, accumulate=False):
        mesh = self.mesh
        if self.zero_offload and mesh is not None:
            # offloaded optimizer states live in pinned host memory between
            # steps; XLA compute requires device space, so the step opens
            # with an explicit host->HBM transfer (and closes with the
            # device_put back to host below)
            ospecs = self.opt_state_specs(state["opt"], self.param_specs())
            opt_dev = {}
            for slot, val in state["opt"].items():
                if isinstance(val, dict):
                    opt_dev[slot] = {
                        k: (jax.device_put(v, _named(mesh, ospecs[slot][k]))
                            if v is not None else None)
                        for k, v in val.items()}
                else:
                    opt_dev[slot] = val
            state = {**state, "opt": opt_dev}
        if mesh is not None:
            batch = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, _named(mesh, self.batch_spec)) if hasattr(x, "ndim") and x.ndim > 0 else x,
                batch)
        params = state["params"]
        train = {k: v for k, v in params.items() if self._mask.get(k, True)}
        frozen = {k: v for k, v in params.items() if not self._mask.get(k, True)}
        key = jax.random.fold_in(state["rng"], state["step"])
        scaler_state = state.get("scaler")
        grad_fn = jax.value_and_grad(self._loss, has_aux=True)
        (scaled, loss), grads = grad_fn(train, frozen, batch, key, scaler_state)
        if self.scaler is not None and self.scaler.enable:
            grads, scaler_state = self.scaler.unscale_and_update(grads, scaler_state)
        if accumulate:
            # no_sync microstep (reference: DataParallel.no_sync suppresses
            # the Reducer all-reduce): stage grads by SUM — callers scale
            # the loss by 1/accumulate_steps, exactly as with the
            # reference — and leave params/optimizer untouched
            new_state = {
                **state,
                "acc_grads": {k: state["acc_grads"][k] + g
                              for k, g in grads.items()},
                "step": state["step"] + 1}
            if scaler_state is not None:
                new_state["scaler"] = {
                    k: scaler_state[k]
                    for k in ("scale", "good_steps", "bad_steps")}
                # overflow on ANY microstep must skip the whole accumulated
                # update (reference scaler semantics) — sticky until the
                # update step consumes it
                new_state["scaler"]["acc_found_inf"] = (
                    state["scaler"].get("acc_found_inf", jnp.asarray(False))
                    | scaler_state.get("found_inf", jnp.asarray(False)))
            metrics = {"loss": loss,
                       "lr": _current_lr(self.optimizer,
                                         {"step": state["opt"]["step"]})}
            if self.extra_metrics is not None:
                metrics.update(self.extra_metrics(new_state, batch))
            return new_state, metrics
        if "acc_grads" in state:
            grads = {k: g + state["acc_grads"][k] for k, g in grads.items()}
            if scaler_state is not None and "found_inf" in scaler_state:
                scaler_state = {
                    **scaler_state,
                    "found_inf": scaler_state["found_inf"]
                    | state["scaler"].get("acc_found_inf",
                                          jnp.asarray(False))}
        if mesh is not None:
            pspecs = self.param_specs()
            gspecs = self.grad_specs(grads, pspecs)
            grads = {k: jax.lax.with_sharding_constraint(
                g, _named(mesh, gspecs[k])) for k, g in grads.items()}
        with jax.named_scope("optimizer"):
            new_params, new_opt = self.optimizer.apply(grads, state["opt"], params)
        if scaler_state is not None and "found_inf" in scaler_state:
            # paddle GradScaler semantics: skip the whole optimizer step on
            # overflow (moments/step-count must not advance either)
            keep_old = scaler_state["found_inf"]
            sel = lambda old, new: jax.tree.map(
                lambda o, n: jnp.where(keep_old, o, n) if o is not None else None,
                old, new, is_leaf=lambda x: x is None)
            new_params = sel(params, new_params)
            new_opt = sel(state["opt"], new_opt)
        if self.zero_offload and mesh is not None:
            # keep updated optimizer states in pinned host memory; without
            # this the donated step writes them back to HBM and the offload
            # silently ends after one step
            ospecs = self.opt_state_specs(new_opt, self.param_specs())
            new_opt = {
                slot: ({k: (jax.device_put(v, _named(mesh, ospecs[slot][k],
                                                     host=True))
                            if v is not None else None)
                        for k, v in val.items()}
                       if isinstance(val, dict) else val)
                for slot, val in new_opt.items()}
        new_state = {"params": new_params, "opt": new_opt,
                     "step": state["step"] + 1, "rng": state["rng"]}
        if "acc_grads" in state:
            new_state["acc_grads"] = {
                k: jnp.zeros_like(v) for k, v in state["acc_grads"].items()}
        if scaler_state is not None:
            new_state["scaler"] = {k: scaler_state[k]
                                   for k in ("scale", "good_steps", "bad_steps")}
            if "acc_grads" in state:
                new_state["scaler"]["acc_found_inf"] = jnp.asarray(False)
        # lr from the OPTIMIZER's step counter (it does not advance on
        # overflow-skipped steps, unlike the outer step counter)
        metrics = {"loss": loss,
                   "lr": _current_lr(self.optimizer, {"step": state["opt"]["step"]})}
        if self.extra_metrics is not None:
            metrics.update(self.extra_metrics(new_state, batch))
        return new_state, metrics

    def __call__(self, state, batch, accumulate: Optional[bool] = None):
        if accumulate is None:
            # DataParallel.no_sync() context → accumulate this call
            accumulate = not getattr(self.model, "_grad_sync", True)
        if accumulate and not self._accum:
            raise RuntimeError(
                "gradient accumulation requested but this TrainStep was "
                "built without buffers: wrap the model in "
                "paddle_tpu.DataParallel or pass gradient_accumulation=True")
        # fault-injection site "step": same one-falsy-check discipline as
        # the telemetry hook below (enforced by the same CI gate); fires
        # BEFORE the compiled call so the incoming state is never donated
        # when the supervisor catches the injected failure
        fi = _rs_state.FAULTS[0]
        if fi is not None:
            fi("step")
        # telemetry: exactly ONE falsy check on the disabled path (the
        # distributed/debug.py zero-overhead contract, enforced by the
        # telemetry-overhead CI gate)
        mon = _obs_state.MONITOR[0]
        if mon is not None:
            return mon.timed_step(
                self._site, self.model, batch,
                lambda: self._run(state, batch, accumulate))
        return self._run(state, batch, accumulate)

    def _run(self, state, batch, accumulate):
        if self.mesh is not None:
            with self.mesh:
                return self._compiled(state, batch, accumulate)
        return self._compiled(state, batch, accumulate)

    def lower(self, state, batch):
        # same mesh context as __call__: kernel dispatch (shard_map wrapping
        # of Pallas calls) keys off the active physical mesh during tracing
        if self.mesh is not None:
            with self.mesh:
                return self._compiled.lower(state, batch, False)
        return self._compiled.lower(state, batch, False)


def _current_lr(optimizer, state):
    from ..optimizer import LRScheduler
    lr = optimizer._learning_rate
    if isinstance(lr, LRScheduler):
        return lr.lr_at(state["step"])
    return jnp.asarray(lr, jnp.float32)


# ---------------------------------------------------------------------------
# AOT export (paddle.jit.save / load parity for inference graphs)
# ---------------------------------------------------------------------------

def save(fn, path: str, *example_args, input_spec=None):
    """Serialize a jitted function to StableHLO bytes + npz side-car.

    Reference: paddle.jit.save -> *.pdmodel/*.pdiparams, whose signature
    takes either example tensors or ``input_spec=[InputSpec(...)]``.
    Here the "model" is a serialized StableHLO program (jax.export) that
    can be reloaded and executed without the Python model definition.
    """
    from jax import export as jexport
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    if input_spec is not None and not example_args:
        specs = [s if isinstance(s, InputSpec) else InputSpec(*s)
                 for s in input_spec]
        example_args = tuple(s.to_shape_struct() for s in specs)
    # span: AOT export traces + lowers the whole program — a multi-second
    # cold op worth a first-class slot in the trace/JSONL vocabulary
    with _span("jit.save", path=path):
        exp = jexport.export(jitted)(*example_args)
        with open(path + ".stablehlo", "wb") as f:
            f.write(exp.serialize())
    return path + ".stablehlo"


def load(path: str):
    from jax import export as jexport
    with _span("jit.load", path=path):
        with open(path if path.endswith(".stablehlo") else path + ".stablehlo", "rb") as f:
            exp = jexport.deserialize(f.read())
    return TranslatedLayer(exp.call, path)


# ---------------------------------------------------------------------------
# conversion controls (reference: paddle.jit.{enable_to_static,
# not_to_static, ignore_module} — python/paddle/jit/api.py and
# sot/opcode_translator skip lists)
# ---------------------------------------------------------------------------

_TO_STATIC_ENABLED = [True]
_IGNORED_MODULES: set = set()


def enable_to_static(enable: bool = True):
    """Globally toggle to_static conversion: when off, decorated
    functions run eagerly (the reference's debugging switch)."""
    _TO_STATIC_ENABLED[0] = bool(enable)


def not_to_static(function=None):
    """Decorator: mark a function to stay eager inside to_static capture
    (its body executes at trace time as plain Python)."""
    def mark(fn):
        fn._pdtpu_not_to_static = True
        return fn
    return mark(function) if function is not None else mark


def ignore_module(modules):
    """Register modules whose functions the SOT transform must leave
    untouched (reference: sot skip-module list)."""
    for m in (modules if isinstance(modules, (list, tuple)) else [modules]):
        _IGNORED_MODULES.add(getattr(m, "__name__", str(m)))
    return _IGNORED_MODULES


class TranslatedLayer:
    """Reference: paddle.jit.TranslatedLayer — the callable a jit.load
    returns, Layer-shaped (``__call__``/``eval``/``train`` no-ops for
    inference artifacts).  Wraps the deserialized StableHLO callable."""

    def __init__(self, fn, path=None):
        self._fn = fn
        self._path = path
        self.training = False

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)

    forward = __call__

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError(
            "TranslatedLayer is an inference artifact (AOT StableHLO); "
            "training needs the original Layer")


# public namespace hygiene: no foreign-module re-exports (tools/check_api_compat)
from paddle_tpu._export import public_all as _public_all
__all__ = _public_all(globals())
