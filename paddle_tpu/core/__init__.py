"""Core runtime: dtypes, device placement, global flags.

TPU-native replacement for the reference's device/runtime layer
(paddle/phi/backends/*, paddle/fluid/platform/*).  There is no allocator,
stream, or per-device kernel registry to manage — XLA owns device memory and
scheduling — so this layer reduces to dtype policy, device query/placement,
and the flag system (reference: paddle/common/flags.h, ``paddle.set_flags``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import random  # noqa: F401

Tensor = jax.Array

# ---------------------------------------------------------------------------
# dtypes (paddle dtype name parity)
# ---------------------------------------------------------------------------

_DTYPE_ALIASES: Dict[str, Any] = {
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
uint8 = jnp.uint8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
bool_ = jnp.bool_

_default_dtype = [jnp.float32]


def set_default_dtype(d) -> None:
    _default_dtype[0] = convert_dtype(d)


def get_default_dtype():
    return _default_dtype[0]


def convert_dtype(d):
    """Accept paddle-style strings, numpy dtypes, or jnp dtypes."""
    if d is None:
        return _default_dtype[0]
    if isinstance(d, str):
        if d not in _DTYPE_ALIASES:
            raise ValueError(f"unknown dtype {d!r}")
        return _DTYPE_ALIASES[d]
    return jnp.dtype(d).type if isinstance(d, np.dtype) else d


def dtype_name(d) -> str:
    return jnp.dtype(d).name


def is_floating_point(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def finfo(d):
    return jnp.finfo(convert_dtype(d))


def iinfo(d):
    return jnp.iinfo(convert_dtype(d))


# ---------------------------------------------------------------------------
# device API (paddle.device parity)
# ---------------------------------------------------------------------------

_current_device: list = [None]


def _platform_of(spec: str) -> str:
    return {"tpu": "tpu", "gpu": "gpu", "cpu": "cpu", "xla": "tpu"}.get(spec, spec)


def set_device(device: str):
    """``paddle.device.set_device`` parity: "tpu", "tpu:0", "cpu".

    Migration affordance: reference accelerator names ("gpu:0", "npu",
    "xpu", "cuda") resolve to this host's accelerator with a one-time
    warning — ported scripts run unchanged.
    """
    name, _, idx = device.partition(":")
    fallback = False
    try:
        devs = (jax.devices(_platform_of(name)) if name != "auto"
                else jax.devices())
    except RuntimeError:
        if name in ("gpu", "cuda", "npu", "xpu", "mlu"):
            fallback = True
            devs = jax.devices()
            import warnings
            warnings.warn(
                f"set_device({device!r}): no {name} on this host — using "
                f"the default accelerator ({devs[0].platform}) instead",
                stacklevel=2)
        else:
            raise
    if not idx:
        dev = devs[0]
    elif fallback:
        # indices from the ported script's world don't map here: clamp
        dev = devs[min(max(int(idx), 0), len(devs) - 1)]
    else:
        dev = devs[int(idx)]  # out-of-range stays an IndexError
    _current_device[0] = dev
    jax.config.update("jax_default_device", dev)
    return dev


def get_device() -> str:
    dev = _current_device[0]
    if dev is None:
        dev = jax.devices()[0]
    return f"{dev.platform}:{dev.id}"


def get_all_devices():
    return jax.devices()


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def is_compiled_with_cuda() -> bool:  # API parity; always False on this stack
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def synchronize() -> None:
    """Block until all enqueued device work completes (stream-sync parity)."""
    (jnp.zeros(()) + 0).block_until_ready()


# ---------------------------------------------------------------------------
# flags (paddle.set_flags / FLAGS_* parity; env prefix PDTPU_FLAGS_)
# ---------------------------------------------------------------------------

_FLAG_DEFAULTS: Dict[str, Any] = {
    "check_nan_inf": False,          # FLAGS_check_nan_inf parity -> jax_debug_nans
    "matmul_precision": "default",   # maps to jax default_matmul_precision
    "deterministic": False,          # FLAGS_cudnn_deterministic analogue
    "use_pallas_kernels": True,      # prefer pallas kernels where available
    "remat_policy": "none",          # default rematerialisation policy name
    "log_compiles": False,
}
_flags: Dict[str, Any] = {}


def _flag_from_env(name: str, default):
    raw = os.environ.get(f"PDTPU_FLAGS_{name}")
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    return type(default)(raw) if default is not None else raw


def _apply_flag_side_effect(key: str, v) -> None:
    if key == "check_nan_inf":
        jax.config.update("jax_debug_nans", bool(v))
    elif key == "log_compiles":
        jax.config.update("jax_log_compiles", bool(v))
    elif key == "matmul_precision" and v != "default":
        jax.config.update("jax_default_matmul_precision", v)


for _k, _v in _FLAG_DEFAULTS.items():
    _flags[_k] = _flag_from_env(_k, _v)
    if _flags[_k] != _v:  # env override: apply the jax side effect too
        _apply_flag_side_effect(_k, _flags[_k])


def set_flags(flags: Dict[str, Any]) -> None:
    for k, v in flags.items():
        key = k[6:] if k.startswith("FLAGS_") else k
        if key not in _FLAG_DEFAULTS:
            raise KeyError(f"unknown flag {k!r}; known: {sorted(_FLAG_DEFAULTS)}")
        _flags[key] = v
        _apply_flag_side_effect(key, v)


def get_flags(keys=None) -> Dict[str, Any]:
    if keys is None:
        return dict(_flags)
    if isinstance(keys, str):
        keys = [keys]
    return {k: _flags[k[6:] if k.startswith("FLAGS_") else k] for k in keys}


def seed(s: int):
    random.seed(s)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """``paddle.to_tensor`` parity (place/stop_gradient accepted for API compat)."""
    del stop_gradient
    arr = jnp.asarray(data, dtype=convert_dtype(dtype) if dtype is not None else None)
    if place is not None:
        arr = jax.device_put(arr, _place_to_device(place))
    return arr


def _place_to_device(place):
    """Map paddle Place objects (CPUPlace/TPUPlace/CUDAPlace aliases) onto
    jax devices; raw jax devices/shardings pass through."""
    from ..device import CPUPlace, TPUPlace
    if isinstance(place, CPUPlace):
        cpus = [d for d in jax.devices() if d.platform == "cpu"] or \
            jax.devices("cpu")
        return cpus[0]
    if isinstance(place, TPUPlace):
        accel = [d for d in jax.devices() if d.platform != "cpu"] or \
            jax.devices()
        return accel[min(place.idx, len(accel) - 1)]
    return place
